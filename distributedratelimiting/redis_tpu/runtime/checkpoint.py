"""Planned-restart checkpoints on disk (SURVEY.md §5.4).

The reference needs no checkpointing — all durable state lives in Redis
and clients are stateless. Here the store's HBM arrays ARE the store, so
planned restarts snapshot ``(keys, tokens, ts)`` to a file and restore
re-aligns every timestamp to the new process's clock epoch
(``BucketStore.snapshot``/``restore`` do the pulling and re-alignment;
this module only adds the durable file form). Crash recovery deliberately
stays init-on-miss — wiped state self-heals to "full bucket", exactly the
reference's failover posture (``RedisTokenBucketRateLimiter.cs:210-215``).

Format: one pickle (protocol 5 — numpy arrays serialize as raw buffers),
written atomically via temp-file + rename so a crash mid-write leaves the
previous checkpoint intact. Since v3 the store state is nested as its own
pickle with a CRC-32 over those bytes, so a torn or bit-flipped file is
detected and raised as :class:`SnapshotCorruptError` — a TYPED error
naming the recovery path (delete the file; the store initializes empty
and self-heals, the init-on-miss posture above) — never an opaque
``pickle`` traceback from the middle of a server start.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import zlib

__all__ = ["save_snapshot", "load_snapshot", "SnapshotCorruptError",
           "PlacementMismatchError"]

_MAGIC = "drl-tpu-snapshot"
# v1: initial format (2-tuple wtable keys, no semaphore sections).
# v2: wtable keys widened to 3-tuples; sema_dir/semas sections added.
# v3: store state nested as its own pickle ("snapshot_pickle") with a
#     CRC-32 checksum ("crc32") over those bytes. Since round 6 a v3
#     payload may additionally carry "placement_epoch" (the cluster
#     placement epoch the state was owned under — see runtime/
#     placement.py); absent in older files and for placement-unaware
#     servers, and ignored by older readers (optional payload key).
# Readers accept any version in _COMPAT — a v1/v2 snapshot restores into
# a v3 build (no checksum to verify; restore() treats newer sections as
# optional); an *unknown* (newer) version fails loudly here instead of as
# an opaque KeyError deep in restore() during a rollback.
_VERSION = 3
_COMPAT = frozenset({1, 2, 3})

#: Unpickling failure modes a torn/corrupt file produces. AttributeError/
#: ImportError cover a payload whose pickled class moved or never existed
#: (bit flips in the class name land here); ValueError covers truncated
#: numpy buffer reconstruction.
_UNPICKLE_ERRORS = (pickle.UnpicklingError, EOFError, AttributeError,
                    ImportError, IndexError, ValueError)


class SnapshotCorruptError(ValueError):
    """The checkpoint file is torn or corrupt (truncated write, bit
    flip, checksum mismatch). Recovery: delete the file and restart —
    the store initializes empty and self-heals to full buckets, the
    documented init-on-miss posture. Subclasses :class:`ValueError` so
    pre-typed catches keep working."""


class PlacementMismatchError(SnapshotCorruptError):
    """The checkpoint was written under a different cluster placement
    epoch than the caller expects: its key memberships belong to a
    retired map, and restoring it would let a rejoining node serve (and
    double-admit) keys it no longer owns. Recovery is the same
    init-on-miss fallback as a torn file — which is why this subclasses
    :class:`SnapshotCorruptError`: every existing fallback path already
    does the right thing."""


def save_snapshot(store, path: str,
                  placement_epoch: "int | None" = None) -> None:
    """Pull ``store``'s live state to host and write it to ``path``
    atomically. ``placement_epoch`` stamps the cluster placement epoch
    the state was owned under (placement-aware servers pass it on
    OP_SAVE) so a later restore can be held to the current map."""
    snap_bytes = pickle.dumps(store.snapshot(), protocol=5)
    payload = {
        "magic": _MAGIC,
        "version": _VERSION,
        "crc32": zlib.crc32(snap_bytes),
        "snapshot_pickle": snap_bytes,
    }
    if placement_epoch is not None:
        payload["placement_epoch"] = int(placement_epoch)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".snapshot-")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f, protocol=5)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(store, path: str,
                  expected_placement_epoch: "int | None" = None) -> None:
    """Restore ``store`` from a checkpoint file written by
    :func:`save_snapshot`. Timestamps re-align to this process's clock
    epoch inside ``store.restore``. Only load files you wrote — the format
    is pickle (trusted-operator checkpoint, not an interchange format).

    ``expected_placement_epoch`` holds the file to a cluster placement
    epoch: a mismatch (including a file with no recorded epoch) raises
    :class:`PlacementMismatchError` BEFORE any state is unpickled into
    the store — the rejoining-node init-on-miss gate. ``None`` skips the
    check (single-node and placement-unaware deployments).

    Raises :class:`SnapshotCorruptError` for a torn or bit-flipped file
    (including a v3 checksum mismatch) and plain :class:`ValueError` for
    a file that is simply not a snapshot or speaks an unknown newer
    version."""
    with open(path, "rb") as f:
        try:
            payload = pickle.load(f)
        except _UNPICKLE_ERRORS as exc:
            raise SnapshotCorruptError(
                f"{path} is torn or corrupt ({exc!r}); delete it to fall "
                "back to init-on-miss (state self-heals to full buckets)"
            ) from exc
    if not isinstance(payload, dict) or payload.get("magic") != _MAGIC:
        raise ValueError(f"{path} is not a rate-limiter snapshot")
    if payload.get("version") not in _COMPAT:
        raise ValueError(
            f"snapshot version {payload.get('version')} not supported "
            f"(this build reads {sorted(_COMPAT)})"
        )
    if expected_placement_epoch is not None:
        recorded = payload.get("placement_epoch")
        if recorded != expected_placement_epoch:
            raise PlacementMismatchError(
                f"{path} was written under placement epoch {recorded} "
                f"but the cluster is at epoch {expected_placement_epoch}"
                "; its key memberships are stale — delete it to fall "
                "back to init-on-miss (migration re-ships any state "
                "this node should own)")
    if "snapshot_pickle" in payload:  # v3: verify before unpickling
        blob = payload["snapshot_pickle"]
        crc = zlib.crc32(blob)
        if crc != payload.get("crc32"):
            raise SnapshotCorruptError(
                f"{path} failed its checksum (crc32 {crc:#010x} != "
                f"recorded {payload.get('crc32', 0):#010x}); delete it "
                "to fall back to init-on-miss")
        try:
            snap = pickle.loads(blob)
        except _UNPICKLE_ERRORS as exc:  # pragma: no cover — crc catches
            raise SnapshotCorruptError(                 # almost all of these
                f"{path} snapshot body is corrupt ({exc!r})") from exc
    else:  # v1/v2: the state rides in the outer pickle, no checksum
        if "snapshot" not in payload:
            raise SnapshotCorruptError(
                f"{path} carries neither a v3 snapshot body nor a "
                "v1/v2 'snapshot' section")
        snap = payload["snapshot"]
    store.restore(snap)
