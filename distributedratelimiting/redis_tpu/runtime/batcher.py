"""Asyncio micro-batcher: amortizes kernel-launch cost over concurrent calls.

The reference pays one network round-trip per ``WaitAsync``
(``RedisTokenBucketRateLimiter.cs:63``) and its README names request
batching as the missing piece (``README.md:7``). Here batching is the core
of the design (SURVEY.md §7 L2): concurrent ``acquire`` calls are collected
into a flush — closed when it reaches ``max_batch`` or when the oldest
entry has waited ``max_delay_s`` — and one kernel launch decides the whole
batch. Device transfer/blocking happens on an executor thread so the event
loop keeps accumulating the next flush while the previous one is in flight;
``max_inflight`` bounds the pipeline depth. Result readbacks overlap across
flushes (device→host fetch latency is round-trip-bound, not
bandwidth-bound, on remote/tunneled links — measured: 8 concurrent fetches
cost the same wall time as 1), so a deeper pipeline multiplies end-to-end
throughput without affecting per-batch semantics: kernels themselves still
execute serially in submission order via state-buffer donation.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, Generic, Sequence, TypeVar

from distributedratelimiting.redis_tpu.utils import tracing

TReq = TypeVar("TReq")
TRes = TypeVar("TRes")

#: The process-global tracer, bound once: configure() mutates the same
#: instance, and the submit hot path pays one attribute read, not a
#: function call, to learn tracing is off.
_TRACER = tracing.get_tracer()

__all__ = ["MicroBatcher"]


class MicroBatcher(Generic[TReq, TRes]):
    def __init__(
        self,
        flush_fn: Callable[[Sequence[TReq]], Awaitable[Sequence[TRes]]],
        *,
        max_batch: int = 4096,
        max_delay_s: float = 200e-6,
        max_inflight: int = 8,
        flush_latency=None,
        queue_latency=None,
        flush_observer=None,
    ) -> None:
        if max_batch <= 0:
            raise ValueError("max_batch must be positive")
        self._flush_fn = flush_fn
        self._max_batch = max_batch
        self._max_delay_s = max_delay_s
        # Optional LatencyHistogram: wall time of each flush_fn await
        # (dispatch + kernel + readback) — the device-side share of the
        # serving-latency decomposition.
        self._flush_latency = flush_latency
        # Optional LatencyHistogram: enqueue → flush dispatch, recorded
        # once per flush for the OLDEST member (the conservative envelope
        # of queue wait — per-member records would cost a hist insert per
        # request on the hot path; the oldest member's wait bounds them
        # all and is what drives the p99).
        self._queue_latency = queue_latency
        # Optional callable(n_requests, wall_s, error_repr | None,
        # trace_id | None), fired once per completed flush — the
        # flight-recorder feed (trace_id cross-references the frame to
        # its exported trace).
        self._flush_observer = flush_observer
        # (request, future, enqueue_stamp, trace_ctx). The trace ctx is
        # None on every untraced request — captured only because the
        # flush runs in its own task, where the submitter's context
        # variable does not follow.
        self._pending: list[tuple[TReq, asyncio.Future, float,
                                  "tracing.TraceContext | None"]] = []
        self._timer: asyncio.TimerHandle | None = None
        self._inflight = asyncio.Semaphore(max_inflight)
        self._tasks: set[asyncio.Task] = set()  # strong refs to in-flight flushes
        self._closed = False

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    async def submit(self, request: TReq) -> TRes:
        """Enqueue one request; resolves with its per-request result."""
        if self._closed:
            raise RuntimeError("batcher is closed")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        # The enqueue stamp is one perf_counter read (~60ns) on a path
        # already paying a future + list append; it is what makes the
        # queue stage a measured histogram instead of an inference. The
        # ambient-trace capture costs one contextvar read and is None on
        # the untraced path.
        self._pending.append((request, fut, time.perf_counter(),
                              tracing.current_context()
                              if _TRACER.enabled else None))
        if len(self._pending) >= self._max_batch:
            self._start_flush(loop)
        elif self._timer is None:
            # Flush-on-idle: with no flush in flight there is nothing to
            # overlap the wait with — delay only adds latency (and the
            # loop's timer granularity inflates a µs deadline to ~1ms).
            # call_later(0) still runs after this loop pass, so every
            # same-pass submitter joins the batch. The deadline proper
            # applies only while the pipeline is busy, where in-flight
            # flushes provide the batching back-pressure it exists for.
            delay = 0.0 if not self._tasks else self._max_delay_s
            self._timer = loop.call_later(delay, self._start_flush, loop)
        return await fut

    def _start_flush(self, loop: asyncio.AbstractEventLoop) -> None:
        # Loop-thread-only by design: reached from submit() (a coroutine
        # on `loop`) or from the call_later timer it arms (loop thread by
        # definition) — never from a foreign thread.
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._pending:
            return
        batch = self._pending[: self._max_batch]
        del self._pending[: len(batch)]
        # drl-check: ok(task-off-loop) loop-thread-only (see above)
        task = loop.create_task(self._run_flush(batch))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        # Anything past max_batch re-arms the deadline.
        if self._pending and self._timer is None:
            # drl-check: ok(task-off-loop) loop-thread-only (see above)
            self._timer = loop.call_later(
                self._max_delay_s, self._start_flush, loop
            )

    async def _run_flush(self,
                         batch: list[tuple[TReq, asyncio.Future, float,
                                           "tracing.TraceContext | None"]]
                         ) -> None:
        async with self._inflight:
            requests = [r for r, _, _, _ in batch]
            t0 = time.perf_counter()
            if self._queue_latency is not None:
                # batch[0] is the oldest submitter: its wait envelopes
                # every other member's (arrival order is append order).
                self._queue_latency.record(t0 - batch[0][2])
            # The flush is SHARED: one span, parented on the first traced
            # member (the elected trace); every other traced member's
            # queue span carries flush_span_id so its trace still names
            # the flush it rode. Queue spans are recorded at flush time
            # (enqueue stamp -> dispatch) — no per-request cost beyond
            # the ctx capture in submit().
            elected, elected_enq = next(
                ((c, t) for _, _, t, c in batch if c is not None),
                (None, t0))
            tracer = _TRACER
            fspan = (tracer.start_span("batch.flush", parent=elected,
                                       attrs={"n": len(batch)})
                     if elected is not None else tracing._NULL_SPAN)
            if elected is not None:
                fid = (f"{fspan.context.span_id:016x}"
                       if fspan.context is not None else None)
                for _, _, t_enq, ctx in batch:
                    if ctx is not None:
                        tracer.record_span(
                            "batch.queue", ctx, t_enq, t0,
                            attrs=None if fid is None
                            else {"flush_span_id": fid})
                if self._queue_latency is not None:
                    # The exemplar pairs the elected member's OWN wait
                    # with its trace id — the sample above (oldest
                    # member's envelope) may belong to a different,
                    # untraced request.
                    self._queue_latency.exemplar(t0 - elected_enq,
                                                 elected.trace_id)
            trace_id = None if elected is None else elected.trace_id
            try:
                with fspan:
                    results = await self._flush_fn(requests)
            except BaseException as exc:  # noqa: BLE001 — fan the failure out
                if self._flush_observer is not None:
                    try:
                        self._flush_observer(len(batch),
                                             time.perf_counter() - t0,
                                             repr(exc), trace_id)
                    # observer bugs must not mask the flush failure
                    # drl-check: ok(swallowed-exception)
                    except Exception:  # noqa: BLE001
                        pass
                for _, fut, _, _ in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                return
            dt = time.perf_counter() - t0
            if self._flush_latency is not None:
                self._flush_latency.record(dt, trace_id=trace_id)
            if self._flush_observer is not None:
                try:
                    self._flush_observer(len(batch), dt, None, trace_id)
                # an observer bug must never fail a flush that succeeded
                # drl-check: ok(swallowed-exception)
                except Exception:  # noqa: BLE001
                    pass
            for (_, fut, _, _), res in zip(batch, results):
                if not fut.done():  # caller may have cancelled while queued
                    fut.set_result(res)

    async def flush_now(self) -> None:
        """Force-flush pending requests and wait for every in-flight flush
        to complete — a shutdown drain must not strand submitters on
        futures whose flush task dies with the loop."""
        loop = asyncio.get_running_loop()
        while self._pending:
            self._start_flush(loop)
            await asyncio.sleep(0)
        while self._tasks:
            tasks = list(self._tasks)
            await asyncio.gather(*tasks, return_exceptions=True)
            # Remove the awaited tasks ourselves: their done-callback
            # discards are only QUEUED on the loop, and awaiting a gather
            # whose children are all already finished does not yield — so
            # `while self._tasks` alone livelocks (measured: a tight
            # never-suspending spin) when aclose runs before the callbacks
            # get a loop pass.
            self._tasks.difference_update(tasks)

    async def aclose(self) -> None:
        self._closed = True
        await self.flush_now()
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
