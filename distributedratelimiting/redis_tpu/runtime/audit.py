"""Conservation audit plane — the continuous ε-ledger (DESIGN.md §22).

The framework's correctness story is a stack of documented ε terms:
the tier-0 cache may over-admit ``overadmit_epsilon(...)`` per key
between syncs, a drain/handoff window serves from a bounded fair-share
envelope, the reservation ledger converts estimate error into refunds
or debts, and a federation home that loses a region charges the
conservative worst case. Every one of those bounds lives in a
different subsystem — and "When Two is Worse Than One" (PAPERS.md) is
what happens when the composition drifts and nobody is watching the
sum. This module watches the sum.

:class:`ConservationAuditor` folds the monotonic counter plane into
explicit conservation identities once per tick:

* **reply/witness** — tokens the server TOLD clients it granted vs
  tokens the store ACTUALLY debited (two adjacent counters at the
  scalar decision site). Any positive residue is a token leak — there
  is no ε term that excuses it.
* **reservation** — the ledger's flow identity (reservations.py
  ``conservation()``): reserved + restored-in + extra-debited ==
  settled + refunded + exported-out + dropped + outstanding, exact to
  float noise per node.
* **federation** — home-side charges (+ conservative pending charges
  for expired-unsettled leases) must cover Σ regional reported
  admissions (federation.py ``conservation()``). A NEGATIVE residue
  is global over-admission; positive residue is the documented
  conservative direction and is tolerated.

Everything is DELTA-based via :class:`~..utils.metrics.CounterDeltas`
(the auditor is one more registered consumer of the shared counter
plane — never ``reset=True``), so it composes with scrapers and the
controller without coordination. Realized over-admission accumulates
into ``overadmitted_tokens`` — the SLI numerator the
:class:`~..utils.slo.BurnRateWatchdog` burns against — and ε-budget
utilization per source renders as
``drl_epsilon_budget_used_ratio{source=...}``.

On a conservation breach or a watchdog trip the auditor assembles ONE
black-box incident bundle per episode (hysteresis de-dups the case
where the leak trips both the ledger and the SLO): correlated flight
frames (``kind in ("audit", "slo", "controller")``), the kept traces
matching histogram exemplar trace-ids, the controller's recent action
log, and the raw witnessing counter deltas — a single JSON artifact a
human can read AFTER the incident, which is the whole point of a
black box. Served via ``OP_AUDIT`` / ``GET /audit``.

Determinism contract: ticks are counted, not clocked (the background
task merely calls :meth:`tick`; seeded soaks drive it directly), and
bundles carry no wall-clock-derived identity — same seed, same
schedule, bit for bit.
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import deque

from distributedratelimiting.redis_tpu.utils.metrics import (
    CounterDeltas,
    LatencyHistogram,
)
from distributedratelimiting.redis_tpu.utils.slo import (
    BurnRateWatchdog,
    SLOConfig,
)

__all__ = ["AuditConfig", "ConservationAuditor"]

#: ε sources the utilization gauges are labelled with, fixed order.
EPSILON_SOURCES = ("tier0", "shard", "envelope", "federation")


@dataclasses.dataclass(frozen=True)
class AuditConfig:
    """Knobs of one conservation auditor (docs/OPERATIONS.md §18)."""

    #: Background tick cadence, seconds. Alert LOGIC never reads the
    #: clock — this only paces the asyncio task.
    tick_s: float = 0.5
    #: Absolute slack per identity before a residue reads as a breach
    #: (float noise across f64 token sums; scaled by flow volume).
    tolerance_tokens: float = 1e-6
    #: Tier-0/shard ε budget as a fraction of locally granted tokens —
    #: the audit-side mirror of the headroom fraction the sync pump
    #: hands the cache (utilization 1.0 = drift consumed the whole
    #: documented allowance).
    epsilon_fraction: float = 0.05
    #: Bounded black-box storage: newest ``bundle_cap`` bundles held.
    bundle_cap: int = 8
    #: Per-bundle windows over the correlated evidence streams.
    frame_window: int = 64
    action_window: int = 32
    trace_window: int = 16
    #: Breach hysteresis: this many consecutive clean ticks end an
    #: episode (a flapping identity still yields one bundle).
    clear_ticks: int = 2
    #: The embedded burn-rate watchdog's knobs.
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)


def _bad_latency_samples(hist: "LatencyHistogram | None",
                         slo_s: float) -> tuple[float, float]:
    """(total, above-SLO) CUMULATIVE sample counts from a latency
    histogram — bucket-resolution (a bucket straddling the SLO counts
    as good: the conservative-by-one-bucket direction), delta'd by the
    watchdog's ring, never reset."""
    if hist is None or not hist.total:
        return 0.0, 0.0
    good = 0
    for count, upper in zip(hist.counts,
                            LatencyHistogram.bucket_upper_bounds()):
        if upper <= slo_s:
            good += count
    return float(hist.total), float(hist.total - good)


class ConservationAuditor:
    """Continuous ε-ledger + SLO watchdog over one server's counter
    plane. Attached by :class:`~.server.BucketStoreServer` (the
    ``audit=`` constructor knob); drives itself from a background task
    in wall-clock deployments and is driven tick-by-tick in seeded
    soaks."""

    def __init__(self, server, cfg: "AuditConfig | None" = None) -> None:
        self.server = server
        self.cfg = cfg or AuditConfig()
        self.ticks = 0
        self.tick_failures = 0
        #: Total breach OBSERVATIONS (one per violated identity per
        #: tick) — the ``drl_audit_breaches`` counter the controller
        #: scrapes.
        self.breaches = 0
        #: Cumulative realized over-admission in tokens: leak residues
        #: + tier-0 drift + federation under-charge growth. The
        #: over-admission SLI numerator.
        self.overadmitted_tokens = 0.0
        self.bundles_assembled = 0
        self.bundles: deque[dict] = deque(maxlen=self.cfg.bundle_cap)
        #: Current ε-budget utilization per source (the
        #: ``drl_epsilon_budget_used_ratio`` gauge values).
        self.epsilon_used = {s: 0.0 for s in EPSILON_SOURCES}
        #: Last tick's residues per identity (0.0 = conserved).
        self.residues: dict[str, float] = {}
        self.watchdog = BurnRateWatchdog(
            self.cfg.slo, flight_recorder=server.flight_recorder,
            on_trip=self._on_slo_trip)
        self._deltas = CounterDeltas()
        # Anchor the delta windows NOW (the counters are zero at server
        # construction): CounterDeltas treats a key's first observation
        # as the baseline, so without this a leak that happens entirely
        # before the first tick would be swallowed into the anchor.
        self._deltas.delta("replied", server.audit_replied_tokens)
        self._deltas.delta("witnessed", server.audit_witnessed_tokens)
        self._deltas.delta("t0_overadmit", 0.0)
        self._fed_under_prev = 0.0
        self._breach_active = False
        self._breach_cold = 0
        self._episode_active = False
        self._pending_reasons: list[str] = []
        self._pending_witness: dict = {}
        self._task = None

    # -- lifecycle -----------------------------------------------------------
    async def run(self) -> None:
        """Background pacer: one :meth:`tick` per ``tick_s``. Failures
        count (``tick_failures``) instead of killing the task — a
        broken auditor must never take serving down with it."""
        import asyncio

        while True:
            await asyncio.sleep(self.cfg.tick_s)
            try:
                self.tick()
            except asyncio.CancelledError:  # pragma: no cover
                raise
            except Exception:
                self.tick_failures += 1

    # -- the ledger tick -----------------------------------------------------
    def tick(self) -> dict:
        """Fold the counter plane into the conservation identities,
        update ε gauges, feed the watchdog, and (on a NEW episode)
        assemble the incident bundle. Returns this tick's summary."""
        self.ticks += 1
        srv = self.server
        self._pending_reasons = []
        breaches: list[str] = []
        residues: dict[str, float] = {}
        witness: dict[str, float] = {}

        # 1. reply/witness identity (the scalar decision site).
        d_rep = self._deltas.delta("replied", srv.audit_replied_tokens)
        d_wit = self._deltas.delta("witnessed", srv.audit_witnessed_tokens)
        leak = d_rep - d_wit
        residues["reply_witness"] = leak
        witness["replied_tokens_delta"] = d_rep
        witness["witnessed_tokens_delta"] = d_wit
        if leak > self.cfg.tolerance_tokens:
            self.overadmitted_tokens += leak
            breaches.append("reply_witness")

        # 2. reservation flow identity.
        led = srv.reservations
        if led is not None and led.active:
            rc = led.conservation()
            res = rc["residue"]
            residues["reservation"] = res
            # Scale tolerance with flow volume: 1e9 tokens of exact f64
            # arithmetic still accumulates representation noise.
            tol = self.cfg.tolerance_tokens * max(1.0, rc["inflow"])
            if abs(res) > tol:
                breaches.append("reservation")
                witness["reservation_conservation"] = rc

        # 3. federation cover identity (negative residue = global
        # over-admission; positive = documented conservative slack).
        fed = srv.federation
        if fed is not None and fed.active:
            fc = fed.conservation()
            res = fc["residue"]
            residues["federation"] = res
            tol = self.cfg.tolerance_tokens * max(1.0, fc["accounted"])
            if res < -tol:
                breaches.append("federation")
                witness["federation_conservation"] = fc
            under = max(0.0, -res)
            self.overadmitted_tokens += max(
                0.0, under - self._fed_under_prev)
            self._fed_under_prev = under
            budget = fc.get("epsilon_budget", 0.0)
            self.epsilon_used["federation"] = (
                min(1.0, fc.get("epsilon_used", 0.0) / budget)
                if budget > 0 else 0.0)

        # 4. tier-0 / per-shard ε utilization (native C counters,
        # witnessed slice-side via fe_t0_eps — both transports).
        native = srv._native
        admitted = srv.audit_witnessed_tokens
        if native is not None:
            t0 = native.tier0_stats() or {}
            grant = float(t0.get("grant_tokens", 0.0))
            over = float(t0.get("overadmit_total", 0.0))
            admitted += grant
            bulk = native.bulk_stats() or {}
            admitted += float(bulk.get("permits_local", 0.0))
            self.overadmitted_tokens += self._deltas.delta(
                "t0_overadmit", over)
            budget = self.cfg.epsilon_fraction * grant
            self.epsilon_used["tier0"] = (min(1.0, over / budget)
                                          if budget > 0 else 0.0)
            slices = native.t0_eps_tokens()
            if slices and sum(slices) > 0:
                # Hottest slice's share of local grants: the per-shard
                # slice bound (DESIGN.md §16) is consumed fastest by
                # the hottest slice, so its share IS the utilization
                # proxy (1/n_shards = perfectly balanced, 1.0 = one
                # slice eats the whole per-node allowance).
                self.epsilon_used["shard"] = max(slices) / sum(slices)

        # 5. envelope ε: share of admissions served from bounded
        # fair-share envelopes (drain windows + placement handoffs) —
        # a conservative share-of-traffic proxy, since the envelopes'
        # token bounds are enforced at grant time, not re-derivable
        # from counters here.
        requests = max(1.0, float(self._requests_served()))
        env = 0.0
        if srv.placement.active:
            env += float(srv.placement.stats().get(
                "envelope_decisions", 0.0))
        self.epsilon_used["envelope"] = min(1.0, env / requests)

        # -- breach bookkeeping / episode hysteresis --
        self.residues = residues
        if breaches:
            self.breaches += len(breaches)
            self._breach_active = True
            self._breach_cold = 0
            fr = srv.flight_recorder
            if fr is not None:
                fr.record("audit", event="conservation_breach",
                          tick=self.ticks, sources=list(breaches),
                          residues={k: round(v, 9)
                                    for k, v in residues.items()},
                          witness=witness)
            self._pending_reasons.extend(
                f"conservation:{b}" for b in breaches)
        elif self._breach_active:
            self._breach_cold += 1
            if self._breach_cold >= self.cfg.clear_ticks:
                self._breach_active = False

        # -- SLO watchdog --
        hist = srv.serving_latency
        slo_s = self.cfg.slo.latency_slo_s
        lat_total, lat_bad = _bad_latency_samples(
            hist, slo_s if slo_s is not None else float("inf"))
        sample = {
            "requests": float(self._requests_served()),
            "shed": float(srv.requests_shed),
            "admitted_tokens": float(admitted),
            "overadmitted_tokens": self.overadmitted_tokens,
            "latency_total": lat_total,
            "latency_bad": lat_bad,
        }
        alerts = self.watchdog.tick(sample)

        # -- one bundle per episode --
        self._pending_witness = witness
        if self._pending_reasons and not self._episode_active:
            self._assemble_bundle(self._pending_reasons, witness)
        self._episode_active = (self._breach_active
                                or bool(self.watchdog.tripped()))
        return {"tick": self.ticks, "breaches": breaches,
                "alerts": alerts, "residues": residues}

    def _requests_served(self) -> int:
        srv = self.server
        if srv._native is not None:
            counts = srv._native.counts()
            return int(counts[0]) if counts else 0
        return srv.requests_served

    def _on_slo_trip(self, dim: str, alert: dict) -> None:
        # Queued, not assembled inline: the episode gate at the end of
        # tick() de-dups a leak that trips both the ledger AND the SLO
        # into the single bundle the black-box contract promises.
        self._pending_reasons.append(f"slo:{dim}")

    # -- black-box incident bundles ------------------------------------------
    def _exemplar_trace_ids(self) -> list[str]:
        """Trace ids pinned by the latency histograms' exemplars — the
        correlation keys from the metrics plane into the kept traces."""
        srv = self.server
        hists: list = [srv.serving_latency, srv.reply_latency]
        metrics = getattr(srv.store, "metrics", None)
        hists.append(getattr(metrics, "queue_latency", None))
        hists.append(getattr(metrics, "flush_latency", None))
        if srv._native is not None:
            hists.extend((srv._native.stage_histograms() or {}).values())
        ids: list[str] = []
        for h in hists:
            ex = getattr(h, "exemplars", None)
            if ex:
                ids.extend(tid for tid, _, _ in ex.values())
        # De-dup preserving order (deterministic under a fixed schedule).
        return list(dict.fromkeys(ids))

    def _assemble_bundle(self, reasons: list[str], witness: dict) -> dict:
        srv = self.server
        fr = srv.flight_recorder
        frames = (fr.frames(kind=("audit", "slo", "controller"))
                  [-self.cfg.frame_window:] if fr is not None else [])
        ids = self._exemplar_trace_ids()
        kept = {t.get("trace_id"): t for t in srv.tracer.traces()}
        traces = [kept[i] for i in ids if i in kept][:self.cfg.trace_window]
        actions = (list(srv.controller.actions)[-self.cfg.action_window:]
                   if srv.controller is not None else [])
        bundle = {
            # Counter-derived id: no wall clock, no randomness — the
            # seeded-soak determinism contract.
            "id": f"bundle-{self.bundles_assembled:04d}",
            "tick": self.ticks,
            "reasons": list(reasons),
            "residues": {k: round(v, 9) for k, v in self.residues.items()},
            "witness_deltas": witness,
            "epsilon_budget_used_ratio": dict(self.epsilon_used),
            "overadmitted_tokens": self.overadmitted_tokens,
            "slo": self.watchdog.snapshot(),
            "flight_frames": frames,
            "trace_ids": ids[:self.cfg.trace_window],
            "traces": traces,
            "controller_actions": actions,
        }
        self.bundles.append(bundle)
        self.bundles_assembled += 1
        if fr is not None:
            fr.record("audit", event="incident_bundle",
                      bundle_id=bundle["id"], reasons=list(reasons))
            # The on-disk black box, when the recorder has a home for
            # dumps: one JSON artifact per bundle, newest-id-named so a
            # post-mortem can ls its way to the incident.
            if fr.dump_dir:
                try:
                    path = os.path.join(fr.dump_dir,
                                        f"{bundle['id']}.json")
                    with open(path, "w", encoding="utf-8") as f:
                        json.dump(bundle, f, default=repr)
                except OSError:  # pragma: no cover — best-effort disk
                    pass
        return bundle

    # -- exposition ----------------------------------------------------------
    def numeric_stats(self) -> dict:
        """Flat numeric dict for ``register_numeric_dict`` — the
        ``drl_audit_*`` families (``overadmitted_tokens`` here is the
        ``drl_audit_overadmitted_tokens`` series SLO_SERIES pins)."""
        out = {
            "ticks": self.ticks,
            "tick_failures": self.tick_failures,
            "breaches": self.breaches,
            "overadmitted_tokens": self.overadmitted_tokens,
            "bundles_assembled": self.bundles_assembled,
            "bundles_held": float(len(self.bundles)),
            "episode_active": float(self._episode_active),
        }
        for source, ratio in self.epsilon_used.items():
            out[f"epsilon_used_{source}"] = round(ratio, 6)
        return out

    def epsilon_series(self) -> list[tuple[dict, float]]:
        """Labelled samples for ``drl_epsilon_budget_used_ratio``."""
        return [({"source": s}, self.epsilon_used[s])
                for s in EPSILON_SOURCES]

    def snapshot(self) -> dict:
        """JSON-shaped status for OP_AUDIT / OP_STATS / GET /audit."""
        out = self.numeric_stats()
        out["residues"] = {k: round(v, 9)
                           for k, v in self.residues.items()}
        out["epsilon_budget_used_ratio"] = dict(self.epsilon_used)
        out["slo"] = self.watchdog.snapshot()
        out["bundle_ids"] = [b["id"] for b in self.bundles]
        return out
