"""Time authority.

Invariant 1 (SURVEY.md §2): the *store* is the single source of truth for
time; clients never supply timestamps. In the reference the Lua kernel calls
Redis ``TIME`` (``TokenBucket/RedisTokenBucketRateLimiter.cs:202-203``). Here
the store's host runtime stamps each kernel launch with ONE monotonic tick
value, so every key in a batch observes the same consistent clock.

``ManualClock`` is the injectable fake used by tests — the kernel math is
deterministic given injected time, which is what makes the L0 layer unit
testable (SURVEY.md §4 implication (a)).
"""

from __future__ import annotations

import time

from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND

__all__ = ["Clock", "MonotonicClock", "ManualClock", "TICKS_PER_SECOND"]


class Clock:
    """Abstract tick source. One tick = 1/1024 s."""

    def now_ticks(self) -> int:
        raise NotImplementedError

    def now_seconds(self) -> float:
        return self.now_ticks() / TICKS_PER_SECOND

    def rebase(self, offset_ticks: int) -> None:
        """Shift the epoch forward so ``now_ticks`` shrinks by
        ``offset_ticks`` — paired with the store's ``rebase_*_epoch``
        kernels to keep int32 tick time far from overflow."""
        raise NotImplementedError


class MonotonicClock(Clock):
    """Monotonic wall-clock ticks since construction.

    Monotonicity means the clock-regression clamp
    (``bucket_math.elapsed_ticks``) only ever engages across *store*
    restarts (epoch reset ≙ Redis failover), exactly the scenario the
    reference designed the clamp for
    (``RedisTokenBucketRateLimiter.cs:177-180``).
    """

    def __init__(self) -> None:
        self._epoch = time.monotonic()

    def now_ticks(self) -> int:
        return int((time.monotonic() - self._epoch) * TICKS_PER_SECOND)

    def rebase(self, offset_ticks: int) -> None:
        """Advance the epoch by ``offset_ticks`` so ``now_ticks`` shrinks by
        the same amount. The store calls this together with the
        ``rebase_*_epoch`` kernels before int32 tick time (~24 days) can
        overflow; elapsed values are invariant under the joint shift."""
        self._epoch += offset_ticks / TICKS_PER_SECOND


class ManualClock(Clock):
    """Deterministic test clock; advanced explicitly, may be set backwards
    to exercise the regression clamp."""

    def __init__(self, start_ticks: int = 0) -> None:
        self._ticks = start_ticks

    def now_ticks(self) -> int:
        return self._ticks

    def advance_ticks(self, ticks: int) -> None:
        self._ticks += ticks

    def advance_seconds(self, seconds: float) -> None:
        self._ticks += int(seconds * TICKS_PER_SECOND)

    def set_ticks(self, ticks: int) -> None:
        self._ticks = ticks

    def rebase(self, offset_ticks: int) -> None:
        self._ticks -= offset_ticks
