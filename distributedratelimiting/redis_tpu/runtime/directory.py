"""Key directories: the host-side ``(key string → device slot)`` map.

In the reference, key routing is Redis's own keyspace — one hash per bucket
key, resolved inside the store (SURVEY.md §2 #2, §5.7: "per-key
partitioning = key concatenation, one Redis hash per partition"). Here the
state lives in HBM slot arrays, so the routing map lives host-side in front
of them, and its per-flush batch resolve is on the serving hot path. Two
interchangeable implementations:

- :class:`NativeKeyDirectory` — C++ open-addressing table
  (``native/directory.cc``) via ctypes: one C call resolves a whole flush.
- :class:`PyKeyDirectory` — dict + free-list, semantically identical; the
  fallback when no compiler is available (``DRL_TPU_NO_NATIVE=1`` forces it).

Shared semantics (both backends, property-tested against each other):
slot ids pop in ascending order from a descending free-list; ``resolve``
allocates on miss and returns ``-1`` once the free-list is dry (caller
sweeps/grows and re-resolves); ``remove_slots`` evicts by slot id and
recycles; ``add_slots`` extends capacity after a table grow.
"""

from __future__ import annotations

import ctypes

import numpy as np

from distributedratelimiting.redis_tpu.utils.native import load_directory_lib

__all__ = ["KeyDirectory", "PyKeyDirectory", "NativeKeyDirectory",
           "make_directory"]


class KeyDirectory:
    """Interface (duck-typed; both impls below)."""

    def resolve_batch(self, keys: list[str]) -> np.ndarray:  # i32[n]
        raise NotImplementedError

    def lookup(self, key: str) -> int | None:
        raise NotImplementedError

    def remove_slots(self, dead: "np.ndarray | list[int]") -> int:
        raise NotImplementedError

    def add_slots(self, start: int, end: int) -> None:
        raise NotImplementedError

    @property
    def free_count(self) -> int:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def to_dict(self) -> dict[str, int]:
        raise NotImplementedError

    def load(self, mapping: dict[str, int], n_slots: int) -> None:
        """Restore path: adopt ``mapping`` wholesale; free-list becomes all
        slots in ``[0, n_slots)`` not present in the mapping, popping in
        ascending order."""
        raise NotImplementedError


class PyKeyDirectory(KeyDirectory):
    def __init__(self, n_slots: int) -> None:
        self._map: dict[str, int] = {}
        self._free: list[int] = list(range(n_slots - 1, -1, -1))

    def resolve_batch(self, keys: list[str]) -> np.ndarray:
        out = np.empty(len(keys), np.int32)
        get = self._map.get
        for i, k in enumerate(keys):
            slot = get(k)
            if slot is None:
                if not self._free:
                    out[i] = -1
                    continue
                slot = self._free.pop()
                self._map[k] = slot
            out[i] = slot
        return out

    def lookup(self, key: str) -> int | None:
        return self._map.get(key)

    def remove_slots(self, dead) -> int:
        # Freed slots are pushed in input order (LIFO reuse) — the exact
        # discipline of the native free-list, so the two backends assign
        # identical slot ids for identical op streams.
        rev = {s: k for k, s in self._map.items()}
        removed = 0
        for s in dead:
            k = rev.pop(int(s), None)
            if k is None:
                continue
            del self._map[k]
            self._free.append(int(s))
            removed += 1
        return removed

    def add_slots(self, start: int, end: int) -> None:
        self._free.extend(range(end - 1, start - 1, -1))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def __len__(self) -> int:
        return len(self._map)

    def to_dict(self) -> dict[str, int]:
        return dict(self._map)

    def load(self, mapping: dict[str, int], n_slots: int) -> None:
        self._map = dict(mapping)
        used = set(self._map.values())
        self._free = [s for s in range(n_slots - 1, -1, -1) if s not in used]


class NativeKeyDirectory(KeyDirectory):
    def __init__(self, n_slots: int, lib: ctypes.CDLL) -> None:
        self._lib = lib
        self._h = lib.dir_new(n_slots)
        if not self._h:
            raise MemoryError("dir_new failed")

    def __del__(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h and getattr(self, "_lib", None) is not None:
            self._lib.dir_free(h)

    def resolve_batch(self, keys) -> np.ndarray:
        out = np.empty(len(keys), np.int32)
        out_ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        blob = getattr(keys, "blob", None)
        if blob is not None:
            # Wire-blob fast path (wire.KeyBlob): the frame's key bytes
            # probe the table directly — no Python strings anywhere.
            self._lib.dir_resolve_batch(
                self._h, blob,
                keys.offsets.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)),
                len(keys), out_ptr,
            )
            return out
        if self._lib.has_pylist:
            # Zero-copy: C reads each str's cached UTF-8 directly.
            if not isinstance(keys, list):
                keys = list(keys)
            r = self._lib.dir_resolve_pylist(self._h, keys, out_ptr)
            if r >= 0:
                return out
            # Non-str element: fall through to the encode path, which will
            # raise the natural AttributeError/TypeError.
        encoded = [k.encode("utf-8", "surrogateescape") for k in keys]
        offsets = np.zeros(len(keys) + 1, np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        self._lib.dir_resolve_batch(
            self._h, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(keys), out_ptr,
        )
        return out

    def lookup(self, key: str) -> int | None:
        kb = key.encode("utf-8", "surrogateescape")
        slot = self._lib.dir_lookup(self._h, kb, len(kb))
        return None if slot < 0 else int(slot)

    def remove_slots(self, dead) -> int:
        arr = np.asarray(list(dead) if not isinstance(dead, np.ndarray) else dead,
                         dtype=np.int32)
        if arr.size == 0:
            return 0
        return int(self._lib.dir_remove_slots(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            arr.size))

    def add_slots(self, start: int, end: int) -> None:
        self._lib.dir_add_slots(self._h, start, end)

    @property
    def free_count(self) -> int:
        return int(self._lib.dir_free_count(self._h))

    @property
    def arena_bytes(self) -> int:
        """Live key bytes (diagnostics; compaction keeps the real arena
        within 2× of this under churn)."""
        return int(self._lib.dir_arena_bytes(self._h))

    def __len__(self) -> int:
        return int(self._lib.dir_size(self._h))

    def to_dict(self) -> dict[str, int]:
        n = len(self)
        if n == 0:
            return {}
        nbytes = int(self._lib.dir_arena_bytes(self._h))
        keys_buf = ctypes.create_string_buffer(max(nbytes, 1))
        offsets = np.empty(n + 1, np.int64)
        slots = np.empty(n, np.int32)
        count = self._lib.dir_dump(
            self._h, keys_buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        raw = keys_buf.raw
        # surrogateescape: the KeyBlob serving lane inserts raw key BYTES
        # (byte-identity keys, wire.py ACQUIRE_MANY notes) — a snapshot
        # must round-trip them, not crash on the first non-UTF-8 key.
        return {
            raw[offsets[i]:offsets[i + 1]].decode(
                "utf-8", "surrogateescape"): int(slots[i])
            for i in range(count)
        }

    def load(self, mapping: dict[str, int], n_slots: int) -> None:
        lib, h = self._lib, self._h
        self._h = None
        lib.dir_free(h)
        self._h = lib.dir_new(n_slots)
        for key, slot in mapping.items():
            # surrogateescape: snapshots from a PyKeyDirectory-backed
            # server may carry byte-identity keys (wire.KeyBlob lane) as
            # surrogate-bearing strs; a strict encode would crash-loop
            # the restore this path exists to serve.
            kb = key.encode("utf-8", "surrogateescape")
            if lib.dir_insert(self._h, kb, len(kb), int(slot)) != 0:
                raise ValueError(f"duplicate key in restore mapping: {key!r}")
        used = set(mapping.values())
        free = np.array([s for s in range(n_slots - 1, -1, -1)
                         if s not in used], dtype=np.int32)
        lib.dir_set_free(
            self._h,
            free.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            free.size)


def make_directory(n_slots: int) -> KeyDirectory:
    """Native if buildable, Python otherwise — transparently equivalent."""
    lib = load_directory_lib()
    if lib is not None:
        try:
            return NativeKeyDirectory(n_slots, lib)
        except Exception as exc:
            # The Python directory is a full functional fallback, but a
            # silently slower serving path is the kind of invisible
            # degradation the chaos plane exists to surface.
            import logging

            logging.getLogger(__name__).warning(
                "native key directory unavailable (%r); falling back to "
                "the Python directory", exc)
    return PyKeyDirectory(n_slots)
