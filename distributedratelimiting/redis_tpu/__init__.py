"""TPU-native distributed rate limiting.

A brand-new framework with the capabilities of
``ReubenBond/DistributedRateLimiting.Redis`` (see ``SURVEY.md`` at the repo
root), re-designed TPU-first:

- Per-key token-bucket state lives as structure-of-arrays in device HBM,
  sharded over a ``jax.sharding.Mesh`` for multi-chip scale.
- The reference's Lua-in-Redis "kernels" (atomic refill-and-decrement,
  decaying global counter) become jitted XLA / Pallas batch kernels; one
  kernel launch amortizes what the reference paid one network round-trip for.
- The store — not the client — remains the time authority: every kernel
  launch receives a single host-injected monotonic ``now`` operand, giving
  all keys in a batch one consistent clock (the property Redis ``TIME``
  provided in the reference).
- The two-level approximate algorithm (local scores + decaying global
  counter + membership-free instance estimation) is preserved, with the
  global tier realized as ``lax.psum`` over the mesh.

Public API parallels .NET's ``System.Threading.RateLimiting`` contract that
the reference implements (``RateLimiter``, ``RateLimitLease``,
``PartitionedRateLimiter``), translated to idiomatic async Python.
"""


__version__ = "0.1.0"

from distributedratelimiting.redis_tpu.models.base import (
    MetadataName,
    RateLimitLease,
    RateLimiterStatistics,
    RateLimiter,
)
from distributedratelimiting.redis_tpu.models.concurrency import (
    ConcurrencyLease,
    ConcurrencyLimiter,
)
from distributedratelimiting.redis_tpu.models.fixed_window import (
    FixedWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
    ConcurrencyLimiterOptions,
    FixedWindowOptions,
    QueueingTokenBucketOptions,
    SlidingWindowOptions,
    TokenBucketOptions,
)
from distributedratelimiting.redis_tpu.models.queueing_token_bucket import (
    QueueingTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.token_bucket import TokenBucketRateLimiter
from distributedratelimiting.redis_tpu.models.approximate import (
    ApproximateTokenBucketRateLimiter,
)
from distributedratelimiting.redis_tpu.models.sliding_window import (
    SlidingWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.models.partitioned import PartitionedRateLimiter
from distributedratelimiting.redis_tpu.models.partitioned_window import (
    PartitionedWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime.store import (
    AcquireResult,
    BucketStore,
    BulkAcquireResult,
    DeviceBucketStore,
    InProcessBucketStore,
    SyncResult,
)
from distributedratelimiting.redis_tpu.runtime.clock import (
    ManualClock,
    MonotonicClock,
    TICKS_PER_SECOND,
)
from distributedratelimiting.redis_tpu.parallel.mesh_store import MeshBucketStore
from distributedratelimiting.redis_tpu.runtime.cluster import ClusterBucketStore
from distributedratelimiting.redis_tpu.runtime.fp_store import (
    FingerprintBucketStore,
)
from distributedratelimiting.redis_tpu.runtime.queueing import QueueProcessingOrder
from distributedratelimiting.redis_tpu.runtime.remote import RemoteBucketStore
from distributedratelimiting.redis_tpu.runtime.server import BucketStoreServer
from distributedratelimiting.redis_tpu.utils.registry import (
    ServiceRegistry,
    add_tpu_approximate_token_bucket_rate_limiter,
    add_tpu_concurrency_limiter,
    add_tpu_fixed_window_rate_limiter,
    add_tpu_queueing_token_bucket_rate_limiter,
    add_tpu_sliding_window_rate_limiter,
    add_tpu_token_bucket_rate_limiter,
)

__all__ = [
    "MetadataName",
    "RateLimitLease",
    "RateLimiterStatistics",
    "RateLimiter",
    "TokenBucketOptions",
    "ApproximateTokenBucketOptions",
    "QueueingTokenBucketOptions",
    "SlidingWindowOptions",
    "FixedWindowOptions",
    "ConcurrencyLimiterOptions",
    "TokenBucketRateLimiter",
    "ApproximateTokenBucketRateLimiter",
    "QueueingTokenBucketRateLimiter",
    "SlidingWindowRateLimiter",
    "FixedWindowRateLimiter",
    "ConcurrencyLimiter",
    "ConcurrencyLease",
    "PartitionedRateLimiter",
    "PartitionedWindowRateLimiter",
    "AcquireResult",
    "BulkAcquireResult",
    "SyncResult",
    "BucketStore",
    "BucketStoreServer",
    "DeviceBucketStore",
    "InProcessBucketStore",
    "ClusterBucketStore",
    "FingerprintBucketStore",
    "MeshBucketStore",
    "RemoteBucketStore",
    "ManualClock",
    "MonotonicClock",
    "TICKS_PER_SECOND",
    "QueueProcessingOrder",
    "ServiceRegistry",
    "add_tpu_token_bucket_rate_limiter",
    "add_tpu_approximate_token_bucket_rate_limiter",
    "add_tpu_queueing_token_bucket_rate_limiter",
    "add_tpu_sliding_window_rate_limiter",
    "add_tpu_fixed_window_rate_limiter",
    "add_tpu_concurrency_limiter",
    "__version__",
]
