"""Fixed-window counter limiter — the last `System.Threading.RateLimiting`
family member (``FixedWindowRateLimiter``).

No reference counterpart (the reference distributes only token buckets);
semantics are the classic fixed window: consumption counts against the
window containing ``now`` only, and the count resets at every window
boundary (admitting the well-known 2× boundary burst the sliding variant
exists to smooth). Everything else — contract, lease/metadata handling,
device window table, atomicity, time authority, TTL sweeps — is the
sliding limiter's; only the store call differs (the kernel skips the
trailing-window interpolation), so this subclasses
:class:`~.sliding_window.SlidingWindowRateLimiter` and overrides the two
store-call hooks.
"""

from __future__ import annotations

from distributedratelimiting.redis_tpu.models.options import FixedWindowOptions
from distributedratelimiting.redis_tpu.models.sliding_window import (
    SlidingWindowRateLimiter,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore

__all__ = ["FixedWindowRateLimiter"]


class FixedWindowRateLimiter(SlidingWindowRateLimiter):
    def __init__(self, options: FixedWindowOptions,
                 store: BucketStore) -> None:
        super().__init__(options, store)  # type: ignore[arg-type]

    def _store_acquire_blocking(self, permits: int):
        return self.store.fixed_window_acquire_blocking(
            self.options.instance_name, permits, self.options.permit_limit,
            self.options.window_s,
        )

    async def _store_acquire(self, permits: int):
        return await self.store.fixed_window_acquire(
            self.options.instance_name, permits, self.options.permit_limit,
            self.options.window_s,
        )

    def _retry_after(self, permits: int, remaining: float) -> float:
        # Fixed windows release nothing until the boundary; the window
        # phase lives with the store (time authority), so the sure bound
        # is one full window.
        return self.options.window_s
