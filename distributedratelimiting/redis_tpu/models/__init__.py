"""Limiter model families: the public API surface.

The ``models/`` package holds the client-side policy layer — the analogue of
the reference's L2 limiter layer (SURVEY.md §1): exact and approximate token
buckets, the sliding-window variant, and the partitioned (per-key) façade,
all implementing a Python translation of the
``System.Threading.RateLimiting.RateLimiter`` contract.
"""
