"""Approximate token-bucket limiter — the flagship two-level algorithm.

Capability mirror of ``RedisApproximateTokenBucketRateLimiter``
(``ApproximateTokenBucket/RedisApproximateTokenBucketRateLimiter.cs``), the
reference's headline design (SURVEY.md §2 #3, invariant 6):

- **Decisions are local** — zero store traffic on the hot path
  (``AcquireCore`` ``:84-113``): a lock-guarded local throttle score
  against the fair-share availability formula
  ``max(0, ceil((token_limit − global_score) / instance_count) − local_score)``
  (``:37``).
- **A periodic sync** pushes the harvested local score into the store's
  decaying global counter and pulls back ``(global_score, period_ewma)``
  (``Refresh``/``RefreshAsync`` ``:397-508``). The EWMA of observed
  inter-sync intervals yields the membership-free instance-count estimate
  (``:443``) — clients joining/leaving reshapes everyone's share within
  ~O(period) with no membership protocol (SURVEY.md §5.3d).
- **Degraded mode**: sync failures are logged and skipped; the limiter
  keeps serving from the last-known global score — availability over
  accuracy (``:419-428,437-449``, invariant 9).
- **Queueing**: full waiter semantics (cumulative-permit queue limit,
  oldest/newest-first, eviction, cancellation, dispose-fails-waiters) via
  :class:`~.runtime.queueing.WaiterQueue` — with the reference's
  cancelled-waiter double-count defect fixed by construction.

Staleness bound: decisions may over-admit by at most what peers consume
within one ``replenishment_period_s`` — identical to the reference's bound.
"""

from __future__ import annotations

import asyncio
import math
import time

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    RateLimiter,
    check_permits,
)
from distributedratelimiting.redis_tpu.models.options import (
    ApproximateTokenBucketOptions,
)
from distributedratelimiting.redis_tpu.ops.bucket_math import TICKS_PER_SECOND
from distributedratelimiting.redis_tpu.runtime.queueing import WaiterQueue
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils import log
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = [
    "ApproximateTokenBucketRateLimiter",
    "headroom_budget",
    "overadmit_epsilon",
]


# -- shared local-replica policy (this limiter + the tier-0 edge cache) ----
#
# The native front-end's tier-0 admission cache (native/frontend.cc) is
# this file's algorithm re-hosted below the wire: local decisions against
# a replicated envelope, reconciled by an async sync. Both layers size
# their local confidence with the same two formulas so the documented
# over-admission bound holds everywhere it is quoted (docs/OPERATIONS.md
# "Tier-0 approximate admission"; the C mirror is ``t0_budget_of`` in
# native/frontend.cc — keep the three in sync).

def headroom_budget(available: float, *, fraction: float = 0.5,
                    min_budget: float = 64.0,
                    max_budget: float = float(1 << 20)) -> float:
    """Confident local admission budget carved from an observed
    availability: ``floor(min(available × fraction, max_budget))``, or 0
    when that falls below ``min_budget`` (too little headroom to be worth
    — or safe — deciding locally; the caller must fall through to the
    authoritative path)."""
    b = min(available * fraction, max_budget)
    return float(math.floor(b)) if b >= min_budget else 0.0


def overadmit_epsilon(budget: float, fill_rate_per_sec: float,
                      sync_period_s: float) -> float:
    """Worst-case over-admission of a local replica admitting against a
    budget refreshed every ``sync_period_s``: one budget of grants may be
    outstanding (harvested but not yet debited) while a second budget is
    admitted against the stale envelope, plus whatever the authority
    refills during one sync period — ``2·budget + fill_rate·period``.
    This is the epsilon the tier-0 differential test audits, and (with
    ``budget = 0``) the classic staleness bound of this limiter: peers'
    consumption within one replenishment period."""
    return 2.0 * budget + fill_rate_per_sec * sync_period_s


class ApproximateTokenBucketRateLimiter(RateLimiter):
    def __init__(self, options: ApproximateTokenBucketOptions,
                 store: BucketStore) -> None:
        self.options = options
        self.store = store
        self.metrics = LimiterMetrics()
        self._local_score = 0.0       # ≙ _localThrottleScore
        self._global_score = 0.0      # ≙ _globalThrottleScore
        self._instance_count = 1      # ≙ _instanceCountEstimate
        self._consumed_total = 0.0    # lifetime consumption (diagnostics)
        self._queue = WaiterQueue(options.queue_limit,
                                  options.queue_processing_order)
        self._idle_since: float | None = time.monotonic()
        self._refresh_task: asyncio.Task | None = None
        self._refresh_running = False
        self._last_refresh_mono = time.monotonic()
        self._disposed = False

    # -- availability (the formula, :37) -----------------------------------
    @property
    def available_tokens(self) -> float:
        share = math.ceil(
            (self.options.token_limit - self._global_score)
            / max(1, self._instance_count)
        )
        return max(0.0, share - self._local_score)

    # -- hot path ----------------------------------------------------------
    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.token_limit)  # ≙ :87-90
        if self._disposed:
            raise RuntimeError("limiter is disposed")

    def _try_lease(self, permits: int) -> bool:
        """≙ ``TryLeaseUnsynchronized`` (``:185-214``): grant only when
        permits are available AND no waiter would be overtaken (queue empty,
        or NEWEST_FIRST where overtaking is the policy, ``:202``)."""
        from distributedratelimiting.redis_tpu.runtime.queueing import (
            QueueProcessingOrder,
        )

        if self.available_tokens >= permits and (
            len(self._queue) == 0
            or self.options.queue_processing_order
            is QueueProcessingOrder.NEWEST_FIRST
        ):
            self._consume(permits)
            return True
        return False

    def _consume(self, permits: float) -> None:
        self._local_score += permits
        self._consumed_total += permits
        if permits > 0:
            self._idle_since = None

    def _failed_lease(self, permits: int) -> RateLimitLease:
        """Failed lease with corrected ``retry_after`` (deficit / rate —
        the reference multiplies, ``:393-394``, a known defect)."""
        deficit = permits - self.available_tokens
        rate = self.options.fill_rate_per_second
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: max(0.0, deficit / rate),
        })

    def acquire(self, permits: int = 1) -> RateLimitLease:
        """≙ ``AcquireCore`` (``:84-113``) — purely local, no store I/O on
        the decision itself. The reference arms its sync ``Timer`` in the
        constructor (``:77``); a Python limiter may live entirely outside an
        event loop, so the sync path self-paces: if no refresh task exists
        and a replenishment period has elapsed, one inline blocking sync
        runs here (amortized — once per period, not per call)."""
        self._check_permits(permits)
        self._maybe_refresh_inline()
        if permits == 0:
            # Zero-permit probe (:93-102).
            ok = self.available_tokens > 0
            self.metrics.record_decision(ok)
            return SUCCESSFUL_LEASE if ok else self._failed_lease(0)
        if self._try_lease(permits):
            self.metrics.record_decision(True)
            return SUCCESSFUL_LEASE
        self.metrics.record_decision(False)
        return self._failed_lease(permits)

    def acquire_many(self, permits) -> "BulkAcquireResult":
        """Vectorized local bulk admission: decide a whole batch of permit
        requests against this bucket in ONE numpy pass — no per-request
        Python on the hot loop. Decisions use the same conservative
        in-batch serialization as the device bulk paths: earlier requests'
        demand reserves ahead of later ones within the call (cumulative
        prefix vs the availability at call start), so over-admission is
        impossible and the result equals a sequential replay whenever all
        in-call requests fit. Zero-count probes grant while any
        availability remains at their position. Skipped when waiters are
        queued under OLDEST_FIRST (bulk callers must not overtake parked
        requests — the same gate as ``_try_lease``)."""
        import numpy as np

        from distributedratelimiting.redis_tpu.runtime.queueing import (
            QueueProcessingOrder,
        )
        from distributedratelimiting.redis_tpu.runtime.store import (
            BulkAcquireResult,
        )

        counts = np.asarray(permits, np.int64)
        if counts.size and (counts.min() < 0
                            or counts.max() > self.options.token_limit):
            self._check_permits(int(counts.min()))
            self._check_permits(int(counts.max()))
        if self._disposed:
            raise RuntimeError("limiter is disposed")
        self._maybe_refresh_inline()
        n = counts.size
        avail0 = self.available_tokens
        if len(self._queue) > 0 and (
                self.options.queue_processing_order
                is QueueProcessingOrder.OLDEST_FIRST):
            # Demand must not overtake parked waiters — but probes consume
            # nothing, so they mirror acquire(0): granted while tokens
            # remain (nothing else in the call is granted, so no prefix).
            granted = (counts == 0) & (avail0 > 0)
            remaining = np.full(n, max(avail0, 0.0), np.float32)
            self.metrics.record_bulk(n, int(granted.sum()))
            return BulkAcquireResult(granted, remaining)
        cum = np.cumsum(counts)
        before = cum - counts
        granted = np.where(counts > 0, cum <= avail0, avail0 - before > 0)
        total = int(counts[granted & (counts > 0)].sum()) if n else 0
        if total:
            self._consume(total)
        remaining = np.maximum(avail0 - cum, 0.0).astype(np.float32)
        self.metrics.record_bulk(n, int(granted.sum()))
        return BulkAcquireResult(granted, remaining)

    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        """≙ ``WaitAsyncCore`` (``:116-183``): fast path, then park."""
        self._check_permits(permits)
        self._ensure_refresh_task()
        if permits == 0:
            ok = self.available_tokens > 0
            self.metrics.record_decision(ok)
            return SUCCESSFUL_LEASE if ok else self._failed_lease(0)
        if self._try_lease(permits):
            self.metrics.record_decision(True)
            return SUCCESSFUL_LEASE
        # Queue handling (:139-181).
        future, evicted = self._queue.try_enqueue(permits)
        for victim in evicted:
            self.metrics.evicted += 1
            victim.future.set_result(self._failed_lease(victim.count))
        if future is None:
            self.metrics.record_decision(False)
            return self._failed_lease(permits)
        self.metrics.queued += 1
        try:
            lease = await future
        except asyncio.CancelledError:
            self.metrics.cancelled += 1
            raise
        self.metrics.record_decision(lease.is_acquired)
        return lease

    # -- background sync (the only distributed communication) --------------
    def _maybe_refresh_inline(self) -> None:
        """Loop-less callers get a blocking refresh once per period; callers
        on an event loop get the background task instead."""
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            self._ensure_refresh_task()
            return
        if (self._refresh_task is None
                and time.monotonic() - self._last_refresh_mono
                >= self.options.replenishment_period_s):
            self.refresh_blocking()

    def refresh_blocking(self) -> None:
        """Synchronous sync round for non-async deployments; same semantics
        as :meth:`refresh` minus the waiter-queue drain (waiters only exist
        on an event loop)."""
        if self._refresh_running:
            return
        self._refresh_running = True
        try:
            harvested, self._local_score = self._local_score, 0.0
            try:
                res = self.store.sync_counter_blocking(
                    self.options.instance_name, harvested,
                    self.options.fill_rate_per_second,
                )
            except Exception as exc:  # degraded mode
                log.error_evaluating_kernel(exc)
                self.metrics.sync_failures += 1
                self._local_score += harvested
                return
            self._apply_sync_result(res)
        finally:
            self._last_refresh_mono = time.monotonic()
            self._refresh_running = False

    def _apply_sync_result(self, res) -> None:
        self._global_score = res.global_score
        # Membership-free instance estimate (:440-443).
        period_ticks = self.options.replenishment_period_s * TICKS_PER_SECOND
        self._instance_count = max(
            1, round(period_ticks / max(res.period_ewma_ticks, 1.0))
        )
        self.metrics.syncs += 1
        if self._consumed_total == 0 and self._idle_since is None:
            self._idle_since = time.monotonic()

    def _ensure_refresh_task(self) -> None:
        if self._refresh_task is None or self._refresh_task.done():
            if not self._disposed:
                self._refresh_task = asyncio.get_running_loop().create_task(
                    self._refresh_loop()
                )

    async def _refresh_loop(self) -> None:
        period = self.options.replenishment_period_s
        while not self._disposed:
            await asyncio.sleep(period)
            await self.refresh()

    async def refresh(self) -> None:
        """One sync round (≙ ``Refresh``→``RefreshAsync``, ``:397-508``).
        Public so tests and manual drivers can step it deterministically."""
        if self._refresh_running:  # timer re-entrancy guard (:402-409)
            return
        self._refresh_running = True
        try:
            t0 = time.perf_counter()
            # Harvest local consumption (:430-435).
            harvested, self._local_score = self._local_score, 0.0
            try:
                res = await self.store.sync_counter(
                    self.options.instance_name, harvested,
                    self.options.fill_rate_per_second,
                )
            except Exception as exc:  # degraded mode (:419-428,437-449)
                log.error_evaluating_kernel(exc)
                self.metrics.sync_failures += 1
                self._local_score += harvested  # restore for next sync
                return
            self._apply_sync_result(res)
            self.metrics.last_sync_lag_s = time.perf_counter() - t0
            # Drain parked waiters while tokens are available (:453-501).
            self._queue.drain(self._drain_grant, lambda: SUCCESSFUL_LEASE)
        finally:
            self._last_refresh_mono = time.monotonic()
            self._refresh_running = False

    def _drain_grant(self, count: int) -> bool:
        if self.available_tokens >= count:
            self._consume(count)
            return True
        return False

    # -- contract ----------------------------------------------------------
    def available_permits(self) -> int:
        return int(self.available_tokens)

    @property
    def idle_duration(self) -> float | None:
        if self._idle_since is None:
            return None
        return time.monotonic() - self._idle_since

    async def aclose(self) -> None:
        """Dispose (≙ ``:274-300``): stop the timer, fail queued waiters."""
        if self._disposed:
            return
        self._disposed = True
        if self._refresh_task is not None:
            self._refresh_task.cancel()
            try:
                await self._refresh_task
            except (asyncio.CancelledError, Exception):
                pass
            self._refresh_task = None
        self._queue.fail_all(lambda: FAILED_LEASE)

    def stats(self) -> dict:
        """≙ the ``ToString()`` diagnostic dump (``:510-513``)."""
        return {
            "consumed_total": self._consumed_total,
            "local_score": self._local_score,
            "global_score": self._global_score,
            "instance_count_estimate": self._instance_count,
            "available_tokens": self.available_tokens,
            "queue_count": self._queue.queue_count,
            # The documented staleness bound, via the shared formula.
            "staleness_epsilon": overadmit_epsilon(
                0.0, self.options.fill_rate_per_second,
                self.options.replenishment_period_s),
            **self.metrics.snapshot(),
        }

    def __str__(self) -> str:
        return (
            f"ApproximateTokenBucketRateLimiter(consumed={self._consumed_total}, "
            f"available={self.available_tokens}, "
            f"peers≈{self._instance_count})"
        )
