"""Options dataclasses — the configuration layer.

Python translation of the reference's ``IOptions<TSelf>`` POCOs (SURVEY.md
§2 #7, §5.6): frozen dataclasses with fail-fast validation and derived
values computed once. Deliberate fixes over the reference:

- ``replenishment_period_s`` must be **> 0** — the reference accepted
  ``TimeSpan.Zero`` (``…Options.cs:59-62``), which made the fill rate
  infinite and degenerated the sync timer (known defect, SURVEY.md §2).
- Validation lives in ``__post_init__`` so an invalid options object cannot
  exist, rather than being deferred to the limiter constructor.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from distributedratelimiting.redis_tpu.runtime.queueing import QueueProcessingOrder

__all__ = [
    "TokenBucketOptions",
    "ApproximateTokenBucketOptions",
    "QueueingTokenBucketOptions",
    "SlidingWindowOptions",
    "FixedWindowOptions",
    "ConcurrencyLimiterOptions",
]


@dataclass(frozen=True)
class TokenBucketOptions:
    """Exact token bucket (≙ ``RedisTokenBucketRateLimiterOptions``).

    ``instance_name`` is the bucket key in the shared store
    (``…Options.cs`` "InstanceName (the bucket key)") — limiter instances
    on any number of hosts that share a store and an instance name share
    one bucket.
    """

    token_limit: int = 100
    tokens_per_period: int = 1
    replenishment_period_s: float = 1.0
    instance_name: str = "rate-limiter"
    #: Expected live key count (partitioned/keyed usage). When set and the
    #: store supports reservation (DeviceBucketStore), the backing table
    #: is pre-sized at limiter construction so the serving path never hits
    #: a growth (grow recompiles kernels for the new size — a p99 cliff
    #: the pre-size avoids entirely; see DESIGN.md "Table growth").
    expected_keys: int | None = None

    def __post_init__(self) -> None:
        if self.token_limit <= 0:
            raise ValueError("token_limit must be > 0")
        if self.tokens_per_period <= 0:
            raise ValueError("tokens_per_period must be > 0")
        if self.replenishment_period_s <= 0:
            raise ValueError(
                "replenishment_period_s must be > 0 (a zero period would "
                "make the fill rate infinite)"
            )
        if self.expected_keys is not None and self.expected_keys <= 0:
            raise ValueError("expected_keys must be > 0 when set")

    @property
    def fill_rate_per_second(self) -> float:
        """Derived ``FillRatePerSecond`` (``…Options.cs:80-85``)."""
        return self.tokens_per_period / self.replenishment_period_s


@dataclass(frozen=True)
class QueueingTokenBucketOptions(TokenBucketOptions):
    """Queueing + exact hybrid options (≙ the orphaned
    ``RedisQueueingTokenBucketRateLimiterOptions`` — its limiter is dead
    code in the reference, ``TokenBucketWithQueue/…Options.cs``; here the
    hybrid is live, see :class:`~.queueing_token_bucket.QueueingTokenBucketRateLimiter`).
    Also the base for every queueing-capable options family, so queueing
    validation lives in exactly one place."""

    queue_limit: int = 0
    queue_processing_order: QueueProcessingOrder = QueueProcessingOrder.OLDEST_FIRST

    def __post_init__(self) -> None:
        super().__post_init__()
        if self.queue_limit < 0:
            raise ValueError("queue_limit must be >= 0")


@dataclass(frozen=True)
class ApproximateTokenBucketOptions(QueueingTokenBucketOptions):
    """Approximate two-level limiter options
    (≙ ``RedisApproximateTokenBucketRateLimiterOptions`` — the same
    queueing surface, ``…Options.cs:44-58``, inherited from
    :class:`QueueingTokenBucketOptions`)."""


@dataclass(frozen=True)
class ConcurrencyLimiterOptions:
    """Concurrency (held-permit) limiter options — the
    ``System.Threading.RateLimiting.ConcurrencyLimiterOptions`` member the
    reference never distributed; ``instance_name`` keys one shared
    semaphore across every host sharing the store."""

    permit_limit: int = 10
    queue_limit: int = 0
    queue_processing_order: QueueProcessingOrder = QueueProcessingOrder.OLDEST_FIRST
    instance_name: str = "rate-limiter"
    #: How often parked waiters re-probe the shared store. Local releases
    #: drain immediately; the poll exists for permits freed by OTHER
    #: instances sharing the semaphore (no cross-instance signal exists —
    #: the same store-mediated-only coordination as the reference's star
    #: topology, where staleness is likewise bounded by a period).
    retry_period_s: float = 0.05

    def __post_init__(self) -> None:
        if self.permit_limit <= 0:
            raise ValueError("permit_limit must be > 0")
        if self.queue_limit < 0:
            raise ValueError("queue_limit must be >= 0")
        if self.retry_period_s <= 0:
            raise ValueError("retry_period_s must be > 0")


@dataclass(frozen=True)
class FixedWindowOptions:
    """Fixed-window counter limiter options (≙
    ``FixedWindowRateLimiterOptions`` from the same family)."""

    permit_limit: int = 100
    window_s: float = 1.0
    instance_name: str = "rate-limiter"
    #: See TokenBucketOptions.expected_keys — pre-sizes the window table.
    expected_keys: int | None = None

    def __post_init__(self) -> None:
        if self.permit_limit <= 0:
            raise ValueError("permit_limit must be > 0")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if self.expected_keys is not None and self.expected_keys <= 0:
            raise ValueError("expected_keys must be > 0 when set")


@dataclass(frozen=True)
class SlidingWindowOptions:
    """Sliding-window counter variant (BASELINE config 4)."""

    permit_limit: int = 100
    window_s: float = 1.0
    instance_name: str = "rate-limiter"
    #: See TokenBucketOptions.expected_keys — pre-sizes the window table.
    expected_keys: int | None = None

    def __post_init__(self) -> None:
        if self.permit_limit <= 0:
            raise ValueError("permit_limit must be > 0")
        if self.window_s <= 0:
            raise ValueError("window_s must be > 0")
        if self.expected_keys is not None and self.expected_keys <= 0:
            raise ValueError("expected_keys must be > 0 when set")
