"""Partitioned (per-key) window limiter — the keyed window façade.

The window analogue of :class:`~.partitioned.PartitionedRateLimiter`
(which completes the reference's dead partitioned component #13,
``TokenBucket/PartitionedRedisTokenBucketRateLimiter.cs:6-213``): one
independent sliding/fixed window per resource, partition key =
``instance_name + separator + str(resource)`` (the reference's
key-concatenation scheme, ``:42``), every partition sharing a single
homogeneous-config device window table so concurrent acquires coalesce
into one kernel launch — and whole key arrays decide in one
``acquire_many`` call (BASELINE config 4's serving shape).
"""

from __future__ import annotations

import time
from typing import Callable

from distributedratelimiting.redis_tpu.models.base import (
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    bulk_permit_counts,
    check_permits,
    sliding_retry_after,
)
from distributedratelimiting.redis_tpu.models.options import (
    FixedWindowOptions,
    SlidingWindowOptions,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["PartitionedWindowRateLimiter"]


class PartitionedWindowRateLimiter:
    """Per-resource window limiting with shared options. Pass
    :class:`SlidingWindowOptions` for the interpolated sliding window or
    :class:`FixedWindowOptions` for boundary-reset fixed windows."""

    def __init__(
        self,
        options: "SlidingWindowOptions | FixedWindowOptions",
        store: BucketStore,
        partition_key: Callable[[object], str] = str,
    ) -> None:
        self.options = options
        self.store = store
        self.partition_key = partition_key
        self.fixed = isinstance(options, FixedWindowOptions)
        self.metrics = LimiterMetrics()

    def _key(self, resource: object) -> str:
        return f"{self.options.instance_name}:{self.partition_key(resource)}"

    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.permit_limit)

    def _retry_after(self, permits: int, remaining: float) -> float:
        if self.fixed:
            # Counts release only at the boundary (phase lives with the
            # store): the sure bound is one full window.
            return self.options.window_s
        return sliding_retry_after(permits, remaining,
                                   self.options.permit_limit,
                                   self.options.window_s)

    def _lease(self, granted: bool, remaining: float, permits: int,
               latency_s: float) -> RateLimitLease:
        self.metrics.record_decision(granted, latency_s)
        if granted:
            return SUCCESSFUL_LEASE
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: self._retry_after(permits, remaining),
        })

    def _store_op(self, blocking: bool):
        if self.fixed:
            return (self.store.fixed_window_acquire_blocking if blocking
                    else self.store.fixed_window_acquire)
        return (self.store.window_acquire_blocking if blocking
                else self.store.window_acquire)

    def acquire(self, resource: object, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE
        t0 = time.perf_counter()
        res = self._store_op(blocking=True)(
            self._key(resource), permits, self.options.permit_limit,
            self.options.window_s)
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    async def acquire_async(self, resource: object,
                            permits: int = 1) -> RateLimitLease:
        """Micro-batched: concurrent calls across partitions share one
        kernel launch."""
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE
        t0 = time.perf_counter()
        res = await self._store_op(blocking=False)(
            self._key(resource), permits, self.options.permit_limit,
            self.options.window_s)
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    # -- bulk path ---------------------------------------------------------
    def _bulk_args(self, resources, permits):
        counts = bulk_permit_counts(resources, permits,
                                    self.options.permit_limit)
        return [self._key(r) for r in resources], counts

    async def acquire_many(self, resources: list, permits=1, *,
                           with_remaining: bool = True):
        """Decide many partitions' windows in ONE call (a single await, no
        per-request futures). Returns :class:`~.store.BulkAcquireResult`."""
        keys, counts = self._bulk_args(resources, permits)
        t0 = time.perf_counter()
        res = await self.store.window_acquire_many(
            keys, counts, self.options.permit_limit, self.options.window_s,
            fixed=self.fixed, with_remaining=with_remaining)
        self.metrics.record_bulk(len(res), res.granted_count,
                                 time.perf_counter() - t0)
        return res

    def acquire_many_blocking(self, resources: list, permits=1, *,
                              with_remaining: bool = True):
        keys, counts = self._bulk_args(resources, permits)
        t0 = time.perf_counter()
        res = self.store.window_acquire_many_blocking(
            keys, counts, self.options.permit_limit, self.options.window_s,
            fixed=self.fixed, with_remaining=with_remaining)
        self.metrics.record_bulk(len(res), res.granted_count,
                                 time.perf_counter() - t0)
        return res

    async def aclose(self) -> None:
        pass
