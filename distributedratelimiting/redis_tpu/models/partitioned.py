"""Partitioned (per-key) rate limiter — the batched keyed façade.

The reference sketched this and never shipped it: the entire
``PartitionedRedisTokenBucketRateLimiter`` is commented out
(``TokenBucket/PartitionedRedisTokenBucketRateLimiter.cs:6-213``, dead
component #13), its README naming request batching as the missing piece
(``README.md:7``). This completes the intent the TPU-first way:

- partition key = ``instance_name + separator + str(resource)`` — exactly
  the reference's key-concatenation scheme (``:42``), one independent
  bucket per partition (keys never interact; SURVEY.md §5.7);
- every partition of one limiter shares a single homogeneous-config device
  table, so concurrent ``acquire`` calls across *all* partitions coalesce
  into one kernel launch — the batching the reference never built.
"""

from __future__ import annotations

import time
from typing import Callable

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    bulk_permit_counts,
    check_permits,
)
from distributedratelimiting.redis_tpu.models.options import TokenBucketOptions
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["PartitionedRateLimiter"]


class PartitionedRateLimiter:
    """≙ ``PartitionedRateLimiter<TResource>``: acquire against a resource,
    each resource getting its own token bucket with shared options."""

    def __init__(
        self,
        options: TokenBucketOptions,
        store: BucketStore,
        partition_key: Callable[[object], str] = str,
    ) -> None:
        self.options = options
        self.store = store
        self.partition_key = partition_key
        self.metrics = LimiterMetrics()
        # Lazily-bound per-config hot path (store.acquire_submitter):
        # created on first acquire_async so construction stays device-free.
        self._submit = None

    def _key(self, resource: object) -> str:
        # Key concatenation, one store bucket per partition (dead ref :42).
        return f"{self.options.instance_name}:{self.partition_key(resource)}"

    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.token_limit)

    def _lease(self, granted: bool, remaining: float, permits: int,
               latency_s: float) -> RateLimitLease:
        self.metrics.record_decision(granted, latency_s)
        if granted:
            return SUCCESSFUL_LEASE
        deficit = permits - remaining
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: max(
                0.0, deficit / self.options.fill_rate_per_second
            ),
        })

    def acquire(self, resource: object, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE
        t0 = time.perf_counter()
        res = self.store.acquire_blocking(
            self._key(resource), permits, self.options.token_limit,
            self.options.fill_rate_per_second,
        )
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    async def acquire_async(self, resource: object,
                            permits: int = 1) -> RateLimitLease:
        """Micro-batched: concurrent calls across partitions share one
        kernel launch."""
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE
        submit = self._submit
        if submit is None:
            submit = self._submit = self.store.acquire_submitter(
                self.options.token_limit, self.options.fill_rate_per_second)
            await self.store.connect()
        t0 = time.perf_counter()
        res = await submit(self._key(resource), permits)
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    # -- bulk path ---------------------------------------------------------
    def _bulk_args(self, resources, permits):
        counts = bulk_permit_counts(resources, permits,
                                    self.options.token_limit)
        return [self._key(r) for r in resources], counts

    def _record_bulk(self, res, counts, t0: float) -> None:
        # Zero-permit probes are granted at the STORE layer on every bulk
        # path (BucketStore._grant_probes / the per-request kernel), so the
        # limiter needs no patch-up here.
        self.metrics.record_bulk(len(res), res.granted_count,
                                 time.perf_counter() - t0)

    async def acquire_many(self, resources: list, permits=1, *,
                           with_remaining: bool = True):
        """Decide many partitions in ONE call — a single await, no
        per-request futures (the bulk serving surface; per-request
        ``acquire_async`` remains for latency-sensitive single decisions).
        ``permits`` is an int applied to all, or a per-resource sequence;
        ``with_remaining=False`` skips remaining estimates (verdict-only
        fast path). Returns :class:`~.store.BulkAcquireResult`."""
        keys, counts = self._bulk_args(resources, permits)
        t0 = time.perf_counter()
        res = await self.store.acquire_many(
            keys, counts, self.options.token_limit,
            self.options.fill_rate_per_second,
            with_remaining=with_remaining)
        self._record_bulk(res, counts, t0)
        return res

    def acquire_many_blocking(self, resources: list, permits=1, *,
                              with_remaining: bool = True):
        keys, counts = self._bulk_args(resources, permits)
        t0 = time.perf_counter()
        res = self.store.acquire_many_blocking(
            keys, counts, self.options.token_limit,
            self.options.fill_rate_per_second,
            with_remaining=with_remaining)
        self._record_bulk(res, counts, t0)
        return res

    def available_permits(self, resource: object) -> int:
        return int(self.store.peek_blocking(
            self._key(resource), self.options.token_limit,
            self.options.fill_rate_per_second,
        ))

    def get_statistics(self, resource: object) -> "RateLimiterStatistics":
        """Point-in-time snapshot for one resource (≙ the modern .NET
        ``PartitionedRateLimiter<TResource>.GetStatistics(resource)``).
        Available permits are per-resource (a read-only peek); lease
        counters are limiter-wide — partitions here share one device
        table rather than owning one ``RateLimiter`` each, so per-
        partition lease history isn't tracked (documented deviation).
        Never queues, so ``current_queued_count`` is structurally 0."""
        from distributedratelimiting.redis_tpu.models.base import (
            RateLimiterStatistics,
        )

        return RateLimiterStatistics(
            current_available_permits=self.available_permits(resource),
            total_successful_leases=self.metrics.grants,
            total_failed_leases=self.metrics.denials,
            current_queued_count=0,
        )

    async def aclose(self) -> None:
        pass
