"""Queueing + exact hybrid limiter — the reference's unfinished roadmap item.

The reference ships an entire limiter class commented out
(``TokenBucketWithQueue/RedisTokenBucketRateLimiter.cs:6-549``): a merge of
the exact limiter's store-round-trip grants with the approximate limiter's
waiter queue + periodic refresh machinery. It references undeclared fields
and would not compile — SURVEY.md §2 #14 calls its *intent* ("the roadmap's
queueing + exact-bucket hybrid") the thing worth carrying forward. This is
that limiter, finished:

- Every grant is an **exact** decision against the shared store bucket
  (one micro-batched kernel launch, ≙ one Lua round-trip,
  ``TokenBucket/RedisTokenBucketRateLimiter.cs:176-239``) — no local fair
  share, no staleness.
- An acquire the store declines **parks on the waiter queue** (cumulative
  permit accounting, oldest/newest-first, eviction, cancellation — the
  exact semantics of SURVEY.md §2 #5).
- A **periodic refresh** retries the queue head against the store and
  drains while grants succeed — the analogue of the approximate limiter's
  drain loop (``RedisApproximateTokenBucketRateLimiter.cs:462-501``), but
  each drain grant is a real store round-trip, not a local estimate.
- Degraded mode: a refresh whose store traffic fails is logged and skipped;
  waiters stay parked for the next round (invariant 9).
"""

from __future__ import annotations

import asyncio
import math
import time

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    RateLimiter,
    check_permits,
)
from distributedratelimiting.redis_tpu.models.options import (
    QueueingTokenBucketOptions,
)
from distributedratelimiting.redis_tpu.runtime.queueing import (
    QueueProcessingOrder,
    WaiterQueue,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils import log
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["QueueingTokenBucketRateLimiter"]


class QueueingTokenBucketRateLimiter(RateLimiter):
    def __init__(self, options: QueueingTokenBucketOptions,
                 store: BucketStore) -> None:
        self.options = options
        self.store = store
        self.metrics = LimiterMetrics()
        self._estimated_remaining: float | None = None
        self._queue = WaiterQueue(options.queue_limit,
                                  options.queue_processing_order)
        self._idle_since: float | None = time.monotonic()
        self._refresh_task: asyncio.Task | None = None
        self._refresh_running = False
        self._store_reachable = False  # any store round-trip this round?
        self._disposed = False

    # -- helpers -----------------------------------------------------------
    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.token_limit)
        if self._disposed:
            raise RuntimeError("limiter is disposed")

    def _failed_lease(self, permits: int) -> RateLimitLease:
        remaining = self._estimated_remaining or 0.0
        deficit = permits - remaining
        rate = self.options.fill_rate_per_second
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: max(0.0, deficit / rate),
        })

    async def _store_acquire(self, count: int) -> bool:
        t0 = time.perf_counter()
        res = await self.store.acquire(
            self.options.instance_name, count, self.options.token_limit,
            self.options.fill_rate_per_second,
        )
        self.metrics.acquire_latency.record(time.perf_counter() - t0)
        self._estimated_remaining = res.remaining
        self._store_reachable = True
        return res.granted

    # -- contract ----------------------------------------------------------
    def acquire(self, permits: int = 1) -> RateLimitLease:
        """Synchronous exact attempt; never queues (the contract's sync
        path). The reference's exact sync ``Acquire`` silently always failed
        (``RedisTokenBucketRateLimiter.cs:53-56``, known defect); this one
        performs a real blocking store decision."""
        self._check_permits(permits)
        if permits == 0:
            return (SUCCESSFUL_LEASE if self.available_permits() > 0
                    else self._failed_lease(0))
        t0 = time.perf_counter()
        res = self.store.acquire_blocking(
            self.options.instance_name, permits, self.options.token_limit,
            self.options.fill_rate_per_second,
        )
        self._estimated_remaining = res.remaining
        self.metrics.record_decision(res.granted, time.perf_counter() - t0)
        if res.granted:
            self._idle_since = None
        return SUCCESSFUL_LEASE if res.granted else self._failed_lease(permits)

    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        """Exact store round-trip; on decline, park on the waiter queue to
        be drained by the periodic refresh."""
        self._check_permits(permits)
        self._ensure_refresh_task()
        if permits == 0:
            return (SUCCESSFUL_LEASE if self.available_permits() > 0
                    else self._failed_lease(0))
        # Waiters must not be overtaken under OLDEST_FIRST (same grant gate
        # as the approximate limiter's TryLeaseUnsynchronized, `:202`).
        overtaking_ok = (
            len(self._queue) == 0
            or self.options.queue_processing_order
            is QueueProcessingOrder.NEWEST_FIRST
        )
        if overtaking_ok:
            try:
                granted = await self._store_acquire(permits)
            except Exception as exc:  # degraded mode: store unreachable
                log.could_not_connect_to_store(exc)
                self.metrics.sync_failures += 1
                granted = False
            if granted:
                self.metrics.record_decision(True)
                self._idle_since = None
                return SUCCESSFUL_LEASE
        future, evicted = self._queue.try_enqueue(permits)
        for victim in evicted:
            self.metrics.evicted += 1
            victim.future.set_result(self._failed_lease(victim.count))
        if future is None:
            self.metrics.record_decision(False)
            return self._failed_lease(permits)
        self.metrics.queued += 1
        try:
            lease = await future
        except asyncio.CancelledError:
            self.metrics.cancelled += 1
            raise
        self.metrics.record_decision(lease.is_acquired)
        if lease.is_acquired:
            self._idle_since = None
        return lease

    # -- background refresh -------------------------------------------------
    def _ensure_refresh_task(self) -> None:
        if self._refresh_task is None or self._refresh_task.done():
            if not self._disposed:
                self._refresh_task = asyncio.get_running_loop().create_task(
                    self._refresh_loop()
                )

    async def _refresh_loop(self) -> None:
        period = self.options.replenishment_period_s
        while not self._disposed:
            await asyncio.sleep(period)
            await self.refresh()

    async def refresh(self) -> None:
        """One drain round: retry the queue head against the store, release
        waiters while grants succeed. Public so tests and manual drivers can
        step it deterministically (no wall-clock dependence)."""
        if self._refresh_running:  # timer re-entrancy guard
            return
        self._refresh_running = True
        try:
            t0 = time.perf_counter()
            self._store_reachable = False
            await self._queue.drain_async(
                self._try_drain_grant, lambda: SUCCESSFUL_LEASE
            )
            # A "sync" is a round whose store traffic succeeded (matching
            # the approximate limiter, which counts only successful syncs);
            # failed rounds show up in sync_failures, empty rounds nowhere.
            if self._store_reachable:
                self.metrics.syncs += 1
                self.metrics.last_sync_lag_s = time.perf_counter() - t0
        finally:
            self._refresh_running = False

    async def _try_drain_grant(self, count: int) -> bool:
        try:
            return await self._store_acquire(count)
        except Exception as exc:  # degraded: keep waiters for next round
            log.could_not_connect_to_store(exc)
            self.metrics.sync_failures += 1
            return False

    # -- contract (introspection / lifecycle) -------------------------------
    def available_permits(self) -> int:
        if self._estimated_remaining is None:
            return int(self.store.peek_blocking(
                self.options.instance_name, self.options.token_limit,
                self.options.fill_rate_per_second,
            ))
        return int(math.floor(self._estimated_remaining))

    @property
    def idle_duration(self) -> float | None:
        if self._idle_since is None:
            return None
        return time.monotonic() - self._idle_since

    async def aclose(self) -> None:
        """Dispose: stop the refresh loop, fail all parked waiters."""
        if self._disposed:
            return
        self._disposed = True
        if self._refresh_task is not None:
            self._refresh_task.cancel()
            try:
                await self._refresh_task
            except (asyncio.CancelledError, Exception):
                pass
            self._refresh_task = None
        self._queue.fail_all(lambda: FAILED_LEASE)

    def stats(self) -> dict:
        return {
            "estimated_remaining": self._estimated_remaining,
            "queue_count": self._queue.queue_count,
            **self.metrics.snapshot(),
        }

    def __str__(self) -> str:
        return (
            f"QueueingTokenBucketRateLimiter(bucket={self.options.instance_name!r}, "
            f"estimated_remaining={self._estimated_remaining}, "
            f"queued_permits={self._queue.queue_count})"
        )
