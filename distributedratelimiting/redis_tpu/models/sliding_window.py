"""Sliding-window counter limiter (BASELINE config 4).

No live counterpart exists in the reference (the variant appears only in
the roadmap); semantics follow the standard two-counter interpolated
sliding window, executed store-side with the same atomicity, time-authority
and init-on-miss properties as the token-bucket kernels.
"""

from __future__ import annotations

import math
import time

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    RateLimiter,
    check_permits,
    sliding_retry_after,
)
from distributedratelimiting.redis_tpu.models.options import SlidingWindowOptions
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["SlidingWindowRateLimiter"]


class SlidingWindowRateLimiter(RateLimiter):
    def __init__(self, options: SlidingWindowOptions, store: BucketStore) -> None:
        self.options = options
        self.store = store
        self.metrics = LimiterMetrics()
        self._estimated_remaining: float | None = None
        self._idle_since: float | None = time.monotonic()

    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.permit_limit)

    def _lease(self, granted: bool, remaining: float, permits: int,
               latency_s: float | None = None) -> RateLimitLease:
        self._estimated_remaining = remaining
        self.metrics.record_decision(granted, latency_s)
        if granted:
            if permits > 0:
                self._idle_since = None
            return SUCCESSFUL_LEASE
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: self._retry_after(permits, remaining),
        })

    def _retry_after(self, permits: int, remaining: float) -> float:
        """See :func:`~.base.sliding_retry_after` (single source of truth;
        the fixed-window subclass overrides with the full-window bound)."""
        return sliding_retry_after(permits, remaining,
                                   self.options.permit_limit,
                                   self.options.window_s)

    # Store-call hooks — the fixed-window subclass overrides ONLY these.
    def _store_acquire_blocking(self, permits: int):
        return self.store.window_acquire_blocking(
            self.options.instance_name, permits, self.options.permit_limit,
            self.options.window_s,
        )

    async def _store_acquire(self, permits: int):
        return await self.store.window_acquire(
            self.options.instance_name, permits, self.options.permit_limit,
            self.options.window_s,
        )

    def acquire(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE if self.available_permits() > 0 else FAILED_LEASE
        t0 = time.perf_counter()
        res = self._store_acquire_blocking(permits)
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE if self.available_permits() > 0 else FAILED_LEASE
        t0 = time.perf_counter()
        res = await self._store_acquire(permits)
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    def available_permits(self) -> int:
        if self._estimated_remaining is None:
            return self.options.permit_limit
        return int(math.floor(self._estimated_remaining))

    @property
    def idle_duration(self) -> float | None:
        if self._idle_since is None:
            return None
        return time.monotonic() - self._idle_since

    async def aclose(self) -> None:
        pass
