"""Exact token-bucket limiter: every decision is a store round-trip.

Capability mirror of ``RedisTokenBucketRateLimiter``
(``TokenBucket/RedisTokenBucketRateLimiter.cs``): one limiter instance =
one named bucket in the shared store; every acquire executes the atomic
refill-and-decrement kernel against that bucket (``WaitAsyncCore`` →
``ScriptEvaluateAsync``, ``:58-82``). What the reference paid one Redis RTT
for, this pays one micro-batched kernel launch for — concurrent acquires
across all limiters and partitions sharing a :class:`DeviceBucketStore`
ride the same launch.

Deliberate departures (SURVEY.md §2 defects):
- sync ``acquire`` performs a real blocking decision instead of silently
  always failing (``:53-56``).
- failed leases carry corrected ``retry_after`` metadata
  (``deficit / fill_rate``).
"""

from __future__ import annotations

import math
import time

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    SUCCESSFUL_LEASE,
    MetadataName,
    RateLimitLease,
    RateLimiter,
    check_permits,
)
from distributedratelimiting.redis_tpu.models.options import TokenBucketOptions
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["TokenBucketRateLimiter"]


class TokenBucketRateLimiter(RateLimiter):
    def __init__(self, options: TokenBucketOptions, store: BucketStore) -> None:
        self.options = options
        self.store = store
        self.metrics = LimiterMetrics()
        # ≙ _estimatedRemainingPermits cache (:48-51,67,73): refreshed from
        # every decision's reply, served by available_permits().
        self._estimated_remaining: float | None = None
        self._idle_since: float | None = time.monotonic()

    # -- helpers -----------------------------------------------------------
    def _check_permits(self, permits: int) -> None:
        # ≙ throw-if-over-limit (:87-90 in the approximate variant).
        check_permits(permits, self.options.token_limit)

    def _lease(self, granted: bool, remaining: float, permits: int,
               latency_s: float | None = None) -> RateLimitLease:
        self._estimated_remaining = remaining
        self.metrics.record_decision(granted, latency_s)
        if granted:
            if permits > 0:
                self._idle_since = None
            return SUCCESSFUL_LEASE
        deficit = permits - remaining
        rate = self.options.fill_rate_per_second
        # Corrected retry math: deficit / rate (reference defect inverted it).
        return RateLimitLease(False, {
            MetadataName.RETRY_AFTER: max(0.0, deficit / rate),
        })

    # -- contract ----------------------------------------------------------
    def acquire(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            # Zero-permit probe: succeeds iff tokens are currently available.
            return SUCCESSFUL_LEASE if self.available_permits() > 0 else FAILED_LEASE
        t0 = time.perf_counter()
        res = self.store.acquire_blocking(
            self.options.instance_name, permits, self.options.token_limit,
            self.options.fill_rate_per_second,
        )
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            return SUCCESSFUL_LEASE if self.available_permits() > 0 else FAILED_LEASE
        t0 = time.perf_counter()
        res = await self.store.acquire(
            self.options.instance_name, permits, self.options.token_limit,
            self.options.fill_rate_per_second,
        )
        return self._lease(res.granted, res.remaining, permits,
                           time.perf_counter() - t0)

    def available_permits(self) -> int:
        if self._estimated_remaining is None:
            return int(self.store.peek_blocking(
                self.options.instance_name, self.options.token_limit,
                self.options.fill_rate_per_second,
            ))
        return int(math.floor(self._estimated_remaining))

    @property
    def idle_duration(self) -> float | None:
        if self._idle_since is None:
            return None
        return time.monotonic() - self._idle_since

    async def aclose(self) -> None:
        """The limiter does not own the (shared) store; nothing to stop."""

    def __str__(self) -> str:
        return (
            f"TokenBucketRateLimiter(bucket={self.options.instance_name!r}, "
            f"estimated_remaining={self._estimated_remaining})"
        )
