"""Distributed concurrency limiter — held permits, returned on dispose.

The reference implements only token buckets, but the abstract family it
builds on (``System.Threading.RateLimiting``) also defines
``ConcurrencyLimiter``, whose leases hold permits for the work's duration
and return them on ``Dispose`` — the opposite of token-bucket cost (which
is consumed, never returned; ``models/base.py``). This member completes
the family distributed-ly: the active count lives in the shared store
(:meth:`~.store.BucketStore.concurrency_acquire` /
:meth:`~.store.BucketStore.concurrency_release` — a device semaphore
table under ``DeviceBucketStore``, a wire op under ``RemoteBucketStore``),
so N hosts share one ``permit_limit``.

Queueing mirrors the family contract (cumulative-permit ``queue_limit``,
oldest/newest-first, eviction, cancellation, dispose-fails-waiters) via
the shared :class:`~.queueing.WaiterQueue`. Waiters are drained on every
release: each release tries to hand the freed permits to the queue head
before anyone else sees them.
"""

from __future__ import annotations

import asyncio
import time

from distributedratelimiting.redis_tpu.models.base import (
    FAILED_LEASE,
    RateLimitLease,
    RateLimiter,
    check_permits,
)
from distributedratelimiting.redis_tpu.models.options import (
    ConcurrencyLimiterOptions,
)
from distributedratelimiting.redis_tpu.runtime.queueing import (
    QueueProcessingOrder,
    WaiterQueue,
)
from distributedratelimiting.redis_tpu.runtime.store import BucketStore
from distributedratelimiting.redis_tpu.utils import log
from distributedratelimiting.redis_tpu.utils.metrics import LimiterMetrics

__all__ = ["ConcurrencyLease", "ConcurrencyLimiter"]


class ConcurrencyLease(RateLimitLease):
    """A lease that HOLDS permits: ``dispose``/``__exit__`` returns them to
    the shared store (sync), ``release_async`` from event-loop code."""

    __slots__ = ("_limiter", "_count", "_released")

    def __init__(self, limiter: "ConcurrencyLimiter", count: int) -> None:
        super().__init__(True)
        self._limiter = limiter
        self._count = count
        self._released = False

    def dispose(self) -> None:
        if self._released:
            return  # idempotent — double-dispose must not over-release
        self._released = True
        self._limiter._release_blocking(self._count)

    async def release_async(self) -> None:
        if self._released:
            return
        self._released = True
        await self._limiter._release(self._count)

    async def __aenter__(self) -> "ConcurrencyLease":
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.release_async()


class ConcurrencyLimiter(RateLimiter):
    """≙ ``System.Threading.RateLimiting.ConcurrencyLimiter``, with the
    active count in the shared store (one logical semaphore per
    ``instance_name`` across every host sharing the store)."""

    def __init__(self, options: ConcurrencyLimiterOptions,
                 store: BucketStore) -> None:
        self.options = options
        self.store = store
        self.metrics = LimiterMetrics()
        self._queue = WaiterQueue(options.queue_limit,
                                  options.queue_processing_order)
        self._idle_since: float | None = time.monotonic()
        self._disposed = False
        self._draining = False
        self._drain_again = False
        self._retry_task: asyncio.Task | None = None
        self._drain_tasks: set[asyncio.Task] = set()  # strong refs

    def _check_permits(self, permits: int) -> None:
        check_permits(permits, self.options.permit_limit)
        if self._disposed:
            raise RuntimeError("limiter is disposed")

    def _lease(self, count: int) -> ConcurrencyLease:
        self._idle_since = None
        self.metrics.record_decision(True)
        return ConcurrencyLease(self, count)

    def _failed(self) -> RateLimitLease:
        self.metrics.record_decision(False)
        return FAILED_LEASE

    # -- acquire -----------------------------------------------------------
    def acquire(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:  # zero-permit probe
            ok = self.available_permits() > 0
            self.metrics.record_decision(ok)
            return ConcurrencyLease(self, 0) if ok else FAILED_LEASE
        # Same queue-fairness gate as the async path (≙ the family's
        # TryLeaseUnsynchronized queue check): a sync caller must not
        # overtake parked OLDEST_FIRST waiters.
        if (len(self._queue)
                and self.options.queue_processing_order
                is QueueProcessingOrder.OLDEST_FIRST):
            return self._failed()
        res = self.store.concurrency_acquire_blocking(
            self.options.instance_name, permits, self.options.permit_limit)
        return self._lease(permits) if res.granted else self._failed()

    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        self._check_permits(permits)
        if permits == 0:
            # Async read-only probe — never blocks the event loop.
            res = await self.store.concurrency_acquire(
                self.options.instance_name, 0, self.options.permit_limit)
            ok = self.options.permit_limit - int(res.remaining) > 0
            self.metrics.record_decision(ok)
            return ConcurrencyLease(self, 0) if ok else FAILED_LEASE
        # Fast path only when no waiter would be overtaken (the family's
        # queue-fairness gate, ≙ TryLeaseUnsynchronized's queue check).
        if (len(self._queue) == 0
                or self.options.queue_processing_order
                is QueueProcessingOrder.NEWEST_FIRST):
            # Shield the store round-trip: a cancel that lands mid-flight
            # must not leak permits the store already granted. The op runs
            # to completion; if it granted, the permits go straight back.
            acq = asyncio.ensure_future(self.store.concurrency_acquire(
                self.options.instance_name, permits,
                self.options.permit_limit))
            try:
                res = await asyncio.shield(acq)
            except asyncio.CancelledError:
                self.metrics.cancelled += 1
                # Track a wrapper that awaits the in-flight store op AND its
                # compensating release as ONE drain task: if only the
                # release (created later by a done-callback) were tracked,
                # an aclose() racing the still-in-flight acquire would find
                # nothing to await and the granted permits would strand in
                # the SHARED store.
                cleanup = acq.get_loop().create_task(
                    self._await_release_if_granted(acq, permits))
                self._drain_tasks.add(cleanup)
                cleanup.add_done_callback(self._drain_tasks.discard)
                raise
            if res.granted:
                return self._lease(permits)
        future, evicted = self._queue.try_enqueue(permits)
        for victim in evicted:
            self.metrics.evicted += 1
            victim.future.set_result(FAILED_LEASE)
        if future is None:
            return self._failed()
        self.metrics.queued += 1
        self._ensure_retry_task()
        try:
            lease = await future
        except asyncio.CancelledError:
            self.metrics.cancelled += 1
            # The drain may have granted to this waiter already (future
            # resolved with a held lease, awaiting task cancelled before
            # resuming) — release those permits or they leak forever:
            # sweep_semas never reclaims slots with active > 0.
            if future.done() and not future.cancelled():
                granted = future.result()
                if isinstance(granted, ConcurrencyLease) and granted.is_acquired:
                    self._spawn_release(granted)
            raise
        self.metrics.record_decision(lease.is_acquired)
        return lease

    async def _await_release_if_granted(self, acq: asyncio.Task,
                                        permits: int) -> None:
        """Cleanup for a cancelled-but-shielded store acquire: wait for the
        store's verdict; if it granted, return the permits."""
        try:
            res = await acq
        except (asyncio.CancelledError, Exception):
            return  # acquire never granted — nothing to return
        if res.granted:
            await self.store.concurrency_release(
                self.options.instance_name, permits)

    def _spawn_release(self, lease: ConcurrencyLease) -> None:
        task = asyncio.get_running_loop().create_task(lease.release_async())
        self._drain_tasks.add(task)
        task.add_done_callback(self._drain_tasks.discard)

    def _ensure_retry_task(self) -> None:
        """Parked waiters re-probe the store every ``retry_period_s`` —
        the only way permits released by a DIFFERENT instance sharing the
        semaphore reach local waiters (store-mediated coordination only,
        like everything else in this family). Stops when the queue empties."""
        if self._retry_task is not None and not self._retry_task.done():
            return

        async def loop() -> None:
            while not self._disposed and len(self._queue):
                await asyncio.sleep(self.options.retry_period_s)
                try:
                    await self._drain()
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    # Degraded mode: store unreachable — keep polling, the
                    # waiters outlive the outage (invariant 9's posture).
                    log.error_evaluating_kernel(exc)

        self._retry_task = asyncio.get_running_loop().create_task(loop())

    # -- release + waiter drain --------------------------------------------
    async def _release(self, count: int) -> None:
        await self.store.concurrency_release(
            self.options.instance_name, count)
        await self._drain()
        self._mark_idle_if_unused()

    def _release_blocking(self, count: int) -> None:
        self.store.concurrency_release_blocking(
            self.options.instance_name, count)
        # Waiters only exist on an event loop; schedule a drain if one is
        # running (dispose from sync code on a loop-less thread has no
        # waiters to serve by construction).
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            task = loop.create_task(self._drain_logged())
            # asyncio keeps only weak task refs — an unreferenced drain
            # could be collected mid-await and strand the queue head.
            self._drain_tasks.add(task)
            task.add_done_callback(self._drain_tasks.discard)
        self._mark_idle_if_unused()

    async def _drain_logged(self) -> None:
        try:
            await self._drain()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # degraded mode: waiters wait for retry
            log.error_evaluating_kernel(exc)

    async def _drain(self) -> None:
        """Hand freed permits to parked waiters, oldest/newest-first.
        Single-flight: concurrent releases coalesce onto the running drain
        (which restarts if a release arrived while it ran)."""
        if self._draining:
            self._drain_again = True
            return
        self._draining = True
        try:
            while not self._disposed:
                head = self._queue.peek_next()
                if head is None:
                    if self._drain_again:
                        self._drain_again = False
                        continue
                    break
                res = await self.store.concurrency_acquire(
                    self.options.instance_name, head.count,
                    self.options.permit_limit)
                if not res.granted:
                    if self._drain_again:
                        self._drain_again = False
                        continue
                    break
                # Re-confirm the waiter we acquired for is still next —
                # it may have been cancelled (or the queue failed) during
                # the store round-trip. Held permits are returnable, so
                # the mismatch case releases instead of stranding them
                # (the token-bucket drain can't do this; its cost is
                # consumed — drain_async's documented loss).
                if self._queue.peek_next() is not head or head.future.done():
                    await self.store.concurrency_release(
                        self.options.instance_name, head.count)
                    continue
                self._queue.pop_next()
                self._idle_since = None  # a held lease makes us non-idle
                head.future.set_result(ConcurrencyLease(self, head.count))
        finally:
            self._draining = False

    def _mark_idle_if_unused(self) -> None:
        if self._idle_since is None and len(self._queue) == 0:
            self._idle_since = time.monotonic()

    # -- contract ----------------------------------------------------------
    def available_permits(self) -> int:
        res = self.store.concurrency_acquire_blocking(
            self.options.instance_name, 0, self.options.permit_limit)
        return max(0, self.options.permit_limit - int(res.remaining))

    @property
    def idle_duration(self) -> float | None:
        if self._idle_since is None:
            return None
        return time.monotonic() - self._idle_since

    async def aclose(self) -> None:
        if self._disposed:
            return
        self._disposed = True
        if self._retry_task is not None:
            self._retry_task.cancel()
            try:
                await self._retry_task
            except (asyncio.CancelledError, Exception):
                pass
            self._retry_task = None
        self._queue.fail_all(lambda: FAILED_LEASE)
        # In-flight drain/compensating-release tasks must complete before
        # shutdown — dropping one with the loop would strand permits in
        # the SHARED store (other instances' capacity, not just ours).
        if self._drain_tasks:
            await asyncio.gather(*list(self._drain_tasks),
                                 return_exceptions=True)

    def stats(self) -> dict:
        return {
            "queue_count": self._queue.queue_count,
            **self.metrics.snapshot(),
        }
