"""The ``RateLimiter`` abstract contract and lease types.

A Python translation of the abstract surface the reference implements from
the ``System.Threading.RateLimiting`` package (SURVEY.md §2 invariant 7):

=====================  ====================================
.NET                   here
=====================  ====================================
``Acquire(int)``       ``acquire(permits)`` (sync)
``WaitAsync(int, ct)`` ``await acquire_async(permits)``
``GetAvailablePermits````available_permits()``
``IdleDuration``       ``idle_duration`` (seconds or None)
``Dispose/DisposeAsync````close()`` / ``await aclose()``
``RateLimitLease``     :class:`RateLimitLease`
``MetadataName``       :class:`MetadataName`
=====================  ====================================

Contract points preserved: zero-permit probe semantics, ``ValueError`` when
``permits`` exceeds the configured maximum, disposal fails queued waiters,
failed leases may carry ``retry_after`` metadata. Lease ``dispose`` does NOT
return permits — token-bucket cost is consumed, not held (the reference's
lease classes have no Dispose override; SURVEY.md §2 #9).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Any, Iterable

__all__ = ["MetadataName", "RateLimitLease", "RateLimiter",
           "check_permits", "sliding_retry_after",
           "bulk_permit_counts"]


class MetadataName:
    """Well-known lease metadata keys (≙ ``MetadataName.RetryAfter``,
    ``RedisApproximateTokenBucketRateLimiter.cs:575-585``)."""

    RETRY_AFTER = "RETRY_AFTER"  # seconds (float)
    REASON = "REASON"            # str


class RateLimitLease:
    """Result of an acquire. Shared metadata-free success/failure singletons
    keep the hot path allocation-free, as in the reference
    (``RedisTokenBucketRateLimiter.cs:9-10``)."""

    __slots__ = ("_acquired", "_metadata")

    def __init__(self, acquired: bool, metadata: dict[str, Any] | None = None):
        self._acquired = acquired
        self._metadata = metadata

    @property
    def is_acquired(self) -> bool:
        return self._acquired

    @property
    def metadata_names(self) -> Iterable[str]:
        return tuple(self._metadata) if self._metadata else ()

    def try_get_metadata(self, name: str) -> tuple[bool, Any]:
        if self._metadata and name in self._metadata:
            return True, self._metadata[name]
        return False, None

    @property
    def retry_after(self) -> float | None:
        """Convenience accessor for ``MetadataName.RETRY_AFTER`` seconds."""
        ok, val = self.try_get_metadata(MetadataName.RETRY_AFTER)
        return val if ok else None

    def dispose(self) -> None:
        """No-op: token-bucket cost is consumed, never returned."""

    def __enter__(self) -> "RateLimitLease":
        return self

    def __exit__(self, *exc: object) -> None:
        self.dispose()

    def __bool__(self) -> bool:
        return self._acquired

    def __repr__(self) -> str:
        return f"RateLimitLease(acquired={self._acquired})"


#: Allocation-free shared leases for the metadata-free cases.
SUCCESSFUL_LEASE = RateLimitLease(True)
FAILED_LEASE = RateLimitLease(False)


@dataclass(frozen=True)
class RateLimiterStatistics:
    """≙ ``System.Threading.RateLimiting.RateLimiterStatistics``."""

    current_available_permits: int
    total_successful_leases: int
    total_failed_leases: int
    current_queued_count: int


class RateLimiter(abc.ABC):
    """Abstract rate limiter (≙ ``System.Threading.RateLimiting.RateLimiter``)."""

    @abc.abstractmethod
    def acquire(self, permits: int = 1) -> RateLimitLease:
        """Synchronous attempt; never queues. Zero permits = probe."""

    @abc.abstractmethod
    async def acquire_async(self, permits: int = 1) -> RateLimitLease:
        """Asynchronous acquire; may park on the waiter queue (if the
        limiter has one). Cancellation of the awaiting task unwinds queue
        accounting. Zero permits = probe."""

    @abc.abstractmethod
    def available_permits(self) -> int:
        """Best-effort estimate (≙ ``GetAvailablePermits``; explicitly an
        estimate in the reference, ``RedisTokenBucketRateLimiter.cs:48-51``)."""

    @property
    @abc.abstractmethod
    def idle_duration(self) -> float | None:
        """Seconds since the limiter last had consumption in flight, or
        ``None`` if active (≙ ``IdleDuration``, ``…cs:33-34,503-506``)."""

    def get_statistics(self) -> "RateLimiterStatistics":
        """Point-in-time snapshot (≙ the modern .NET
        ``RateLimiter.GetStatistics()``, which post-dates the reference's
        preview dependency — parity-plus): available permits, lifetime
        successful/failed leases, and the current queued count. Backed by
        the limiter's :class:`~..utils.metrics.LimiterMetrics` (every
        concrete family records decisions there) and the waiter queue
        when the family has one."""
        metrics = getattr(self, "metrics", None)
        queue = getattr(self, "_queue", None)
        return RateLimiterStatistics(
            current_available_permits=self.available_permits(),
            total_successful_leases=(metrics.grants if metrics else 0),
            total_failed_leases=(metrics.denials if metrics else 0),
            # Queued PERMITS, not parked waiters: the .NET
            # ``CurrentQueuedCount`` sums permit counts (the reference's
            # accounting does too, ``RedisTokenBucketRateLimiter.cs:129``
            # ``_queueCount += permitCount``) — a waiter parked for 5
            # permits must report 5, which ``WaiterQueue.queue_count``
            # already tracks.
            current_queued_count=(queue.queue_count
                                  if queue is not None
                                  and hasattr(queue, "queue_count")
                                  else 0),
        )

    @abc.abstractmethod
    async def aclose(self) -> None:
        """Dispose: stop background work, fail queued waiters."""

    def close(self) -> None:
        """Synchronous dispose for non-async contexts."""
        import asyncio

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            asyncio.run(self.aclose())
        else:
            loop.create_task(self.aclose())

    async def __aenter__(self) -> "RateLimiter":
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.aclose()


def check_permits(permits: int, limit: int | float) -> None:
    """Shared argument gate (every limiter family): non-negative, and never
    more than the configured limit — the reference throws the same way
    (``RedisApproximateTokenBucketRateLimiter.cs:87-90``)."""
    if permits < 0:
        raise ValueError("permits must be >= 0")
    if permits > limit:
        raise ValueError(
            f"permits ({permits}) cannot exceed the configured limit "
            f"({limit})"
        )


def sliding_retry_after(permits: int, remaining: float, limit: float,
                        window_s: float) -> float:
    """Earliest time a denied sliding-window request could succeed. The
    interpolated window releases the previous window's count linearly as
    it slides, at most ``limit / window_s`` permits/sec — so covering the
    deficit needs at least ``deficit / limit × window`` seconds (exact
    when the previous window was full; optimistic otherwise), and one full
    window always suffices. Single source of truth for every sliding
    limiter (the fixed-window family returns the full window: counts
    release only at the boundary, whose phase lives with the store)."""
    deficit = permits - remaining
    return min(window_s, max(0.0, deficit / limit * window_s))


def bulk_permit_counts(resources, permits, limit: int | float) -> list[int]:
    """Normalize a bulk call's ``permits`` (int applied to all, or a
    per-resource sequence) into validated per-request counts."""
    if isinstance(permits, int):
        counts = [permits] * len(resources)
    else:
        counts = [int(p) for p in permits]
        if len(counts) != len(resources):
            raise ValueError("permits must be an int or match resources")
    for c in counts:
        check_permits(c, limit)
    return counts
