"""distributedratelimiting — TPU-native distributed rate limiting.

The public package lives in :mod:`distributedratelimiting.redis_tpu`.
"""
