// native/frontend.cc — epoll serving front-end for the store server.
//
// Role in the architecture: BucketStoreServer's socket half, in native
// code. The reference's server-side socket machinery is the Redis server
// itself (a C epoll loop parsing RESP and executing Lua scripts); its
// client half is StackExchange.Redis's multiplexed pipelining connection
// (reference TokenBucket/RedisTokenBucketRateLimiter.cs:111-174
// ConnectAsync; SURVEY.md §5.8). This file plays the Redis-process role
// for the TPU store: it owns the listening socket, parses the v4 wire
// protocol (runtime/wire.py is the format authority), hands
// micro-batches of per-request acquires to Python exactly once per
// flush — so the per-REQUEST Python cost of the serving path drops to
// zero and the per-BATCH cost is one store bulk call — and since round
// 8 serves OP_ACQUIRE_MANY natively too: parse, per-row tier-0
// decisions, and the RESP_BULK encode all run here, with only the
// cold-row residue crossing the ABI as one zero-copy batch. The
// measured per-request asyncio ceiling this replaces is ~13K req/s/core
// with a zero-cost kernel (benchmarks/RESULTS.md "Per-request socket
// ceiling isolated"); everything that ceiling charges per request
// (readexactly, task spawn, decode, encode, write lock) runs here in C.
//
// Threading: one IO thread (epoll) owns all sockets. Python's pump
// thread blocks in fe_wait (GIL released — the library loads via
// ctypes.CDLL, unlike the PyDLL directory) and dispatches batches /
// passthrough frames onto the asyncio loop; completions call
// fe_complete / fe_send from the loop thread. One global mutex guards
// shared state — contention is per-flush and per-event-burst, not
// per-request. Byte order: the wire is little-endian and this file
// assumes an LE host (x86-64/aarch64 — everywhere this framework runs).
//
// Batching policy (mirrors runtime/batcher.py MicroBatcher semantics):
//   flush when (a) pending >= max_batch, (b) the oldest pending request
//   has waited deadline_us (timerfd, ns precision — asyncio timers
//   quantize ~1ms), or (c) the pump is idle and nothing is queued
//   (flush-on-idle: batching only pays when a flush is already in
//   flight; benchmarks/RESULTS.md "flush-on-idle" halved p50).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <sched.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/timerfd.h>
#include <unistd.h>

#if defined(__SANITIZE_THREAD__)
#define DRL_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define DRL_TSAN 1
#endif
#endif

#if defined(__SANITIZE_ADDRESS__)
#define DRL_ASAN 1
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
#define DRL_ASAN 1
#endif
#endif

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <string_view>
#include <chrono>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

#if defined(DRL_TSAN)
// TSan tracks pthread mutexes by ADDRESS. std::mutex's constexpr
// constructor never calls pthread_mutex_init, so when `new Frontend()`
// lands on a heap block where some earlier allocation (ours or an
// uninstrumented library's) destroyed a mutex, TSan still sees the
// destroyed one: every lock of the new mutex reports "mutex is already
// destroyed" and the lost happens-before cascades into hundreds of
// false races. An explicitly initialized mutex makes the birth visible
// to the pthread interceptors. The paired condition variable wraps a
// pthread_cond_t directly for the same reason (std::condition_variable
// demands std::mutex, and condition_variable_any hides another
// constexpr-constructed internal std::mutex that re-creates the exact
// problem). Production builds keep plain std::mutex/condition_variable.
class TsanVisibleMutex {
 public:
  TsanVisibleMutex() { pthread_mutex_init(&m_, nullptr); }
  ~TsanVisibleMutex() { pthread_mutex_destroy(&m_); }
  TsanVisibleMutex(const TsanVisibleMutex&) = delete;
  TsanVisibleMutex& operator=(const TsanVisibleMutex&) = delete;
  void lock() { pthread_mutex_lock(&m_); }
  void unlock() { pthread_mutex_unlock(&m_); }
  bool try_lock() { return pthread_mutex_trylock(&m_) == 0; }
  pthread_mutex_t* native() { return &m_; }

 private:
  pthread_mutex_t m_;
};

class TsanVisibleCondVar {
 public:
  TsanVisibleCondVar() {
    pthread_condattr_t attr;
    pthread_condattr_init(&attr);
    pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
    pthread_cond_init(&c_, &attr);
    pthread_condattr_destroy(&attr);
  }
  ~TsanVisibleCondVar() { pthread_cond_destroy(&c_); }
  TsanVisibleCondVar(const TsanVisibleCondVar&) = delete;
  TsanVisibleCondVar& operator=(const TsanVisibleCondVar&) = delete;
  void notify_one() { pthread_cond_signal(&c_); }
  void notify_all() { pthread_cond_broadcast(&c_); }
  template <class Pred>
  bool wait_for(std::unique_lock<TsanVisibleMutex>& lk,
                std::chrono::milliseconds ms, Pred pred) {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    ts.tv_sec += time_t(ms.count() / 1000);
    ts.tv_nsec += long((ms.count() % 1000) * 1000000);
    if (ts.tv_nsec >= 1000000000L) {
      ts.tv_sec += 1;
      ts.tv_nsec -= 1000000000L;
    }
    while (!pred()) {
      if (pthread_cond_timedwait(&c_, lk.mutex()->native(), &ts) ==
          ETIMEDOUT) {
        return pred();
      }
    }
    return true;
  }

 private:
  pthread_cond_t c_;
};
using FeMutex = TsanVisibleMutex;
using FeCondVar = TsanVisibleCondVar;
#else
using FeMutex = std::mutex;
using FeCondVar = std::condition_variable;
#endif

constexpr uint8_t kVersion = 4;
constexpr uint32_t kMaxFrame = 1u << 20;
constexpr size_t kBodyOff = 6;  // [u8 ver][u32 seq][u8 op]
constexpr size_t kMaxConnOut = 64u << 20;  // runaway outbox => drop conn

constexpr uint8_t OP_ACQUIRE = 1;
constexpr uint8_t OP_WINDOW = 4;
constexpr uint8_t OP_PING = 5;
constexpr uint8_t OP_SEMA = 8;  // signed count: +acquire / -release / 0 probe
constexpr uint8_t OP_FWINDOW = 9;
constexpr uint8_t OP_HELLO = 10;
// Placement / migration control plane (wire.py, round 6): never hot —
// routed to the Python passthrough lane below. Named (and case-listed)
// so drl-check's wire-conformance diff pins their values against
// wire.py and a future fast-path cannot typo them.
constexpr uint8_t OP_PLACEMENT = 14;
constexpr uint8_t OP_PLACEMENT_ANNOUNCE = 15;
constexpr uint8_t OP_MIGRATE_PULL = 16;
constexpr uint8_t OP_MIGRATE_PUSH = 17;
// Live config mutation (wire.py, round 7): control-plane, never hot —
// passthrough like the placement ops. The CONFIG GATE for the fast
// lanes lives in Python (_serve_batch answers retired-config rows with
// the routable "config moved" error; the tier-0 sync pump re-routes a
// retired config's debits and zeroes its replica headroom).
constexpr uint8_t OP_CONFIG = 18;
// Hierarchical tenant → key acquire (wire.py, the token-denominated
// admission plane): its frame carries a tenant extension this parser
// does not speak, so the op MUST stay on the Python passthrough lane —
// named here (never case-listed in the scalar switch) so drl-check's
// wire-hier rule can pin the fallthrough; a future fast-path for it
// must mirror the full [u16 tlen][tenant][f64 ta][f64 tb][u8 priority]
// tail first.
constexpr uint8_t OP_ACQUIRE_H = 19;
// Estimate-reserve-settle lane (wire.py, runtime/reservations.py):
// JSON control frames (TEXT_OPS) against the server-side reservation
// ledger — control-plane cadence, never hot. Passthrough like the
// placement/config ops: named (and case-listed) so drl-check's
// wire-conformance diff pins their values against wire.py and a
// future fast-path cannot typo them.
constexpr uint8_t OP_RESERVE = 20;
constexpr uint8_t OP_SETTLE = 21;
// Global quota federation lane (wire.py, runtime/federation.py): WAN
// lease control frames (TEXT_OPS JSON) against the home ledger —
// WAN-RTT cadence, never hot. Passthrough like the placement/config/
// reservation ops: named (and case-listed) so drl-check's
// wire-conformance diff pins their values against wire.py and a
// future fast-path cannot typo them.
constexpr uint8_t OP_FED_LEASE = 22;
constexpr uint8_t OP_FED_RENEW = 23;
constexpr uint8_t OP_FED_RECLAIM = 24;
// Conservation audit plane (wire.py, runtime/audit.py): JSON audit
// snapshot / incident-bundle surface (TEXT_OPS) — read-only diagnostic
// cadence, never hot. Passthrough like the other control ops: named
// (and case-listed) so drl-check's wire-conformance diff pins its
// value against wire.py and a future fast-path cannot typo it.
constexpr uint8_t OP_AUDIT = 25;

// Bulk admission lane (round 8): OP_ACQUIRE_MANY parses HERE, tier-0
// decides hot bucket rows per-row, and the RESP_BULK reply encodes in C
// — only the cold/uncertain residue crosses the fe_bulk_* ABI, as one
// zero-copy blob+offsets+counts batch (the wire.KeyBlob lane). wire.py
// stays the layout authority; drl-check diffs every constant below
// against it (kBulkReqHead ↔ _BULK_REQ_HEAD et al).
constexpr uint8_t OP_ACQUIRE_MANY = 11;
constexpr size_t kBulkReqHead = 21;   // [u8 flags][f64 a][f64 b][u32 n]
constexpr size_t kBulkRespHead = 5;   // [u8 flags][u32 n]
constexpr uint8_t kBulkFlagRemaining = 1;  // wire _FLAG_WITH_REMAINING
constexpr uint8_t kBulkFlagChained = 8;    // wire _FLAG_CHAINED
constexpr uint8_t kBulkKindMask = 6;       // wire _KIND_MASK (bits 1-2)
constexpr int kBulkKindShift = 1;          // wire _KIND_SHIFT
constexpr uint8_t BULK_KIND_BUCKET = 0;
constexpr uint8_t BULK_KIND_WINDOW = 1;
constexpr uint8_t BULK_KIND_FWINDOW = 2;
// Hierarchical tenant → key bulk frames (wire.py BULK_KIND_HBUCKET):
// carry a tenant extension after the counts array that this parser
// does not speak — handle_bulk_frame's `kind > BULK_KIND_FWINDOW` gate
// routes them to the Python lane (drl-check wire-hier pins the gate).
constexpr uint8_t BULK_KIND_HBUCKET = 3;
// Flags bit 4: the 25-byte trace tail rides after the counts array
// (old decoders read arrays by explicit counts and never see it).
constexpr uint8_t BULK_FLAG_TRACED = 16;

// Op-byte bit 7 (wire.py TRACE_FLAG): a 25-byte trace tail —
// [u64 trace_hi][u64 trace_lo][u64 parent span][u8 flags] — follows the
// payload. Only sampled requests carry it; parsing it here keeps traced
// hot frames on the batch/tier-0 fast lanes instead of demoting them to
// passthrough.
constexpr uint8_t TRACE_FLAG = 0x80;
constexpr size_t kTraceTail = 25;

constexpr uint8_t RESP_DECISION = 64;
constexpr uint8_t RESP_EMPTY = 67;
constexpr uint8_t RESP_BULK = 69;
constexpr uint8_t RESP_ERROR = 127;

// Serving-latency histogram: identical convention to
// utils/metrics.LatencyHistogram (82 log-1.25 buckets from 1µs; a
// quantile reads its bucket's upper edge) so Python can pour these
// counts straight into that class for p50/p99.
constexpr int kHistBuckets = 82;
const double kInvLogBase = 1.0 / std::log(1.25);

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

inline uint16_t rd_u16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline int32_t rd_i32(const uint8_t* p) {
  int32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
inline double rd_f64(const uint8_t* p) {
  double v;
  std::memcpy(&v, p, 8);
  return v;
}
inline void wr_u32(std::string* s, uint32_t v) {
  s->append(reinterpret_cast<const char*>(&v), 4);
}
inline void wr_f64(std::string* s, double v) {
  s->append(reinterpret_cast<const char*>(&v), 8);
}

std::string encode_decision(uint32_t seq, bool granted, double remaining) {
  std::string s;
  s.reserve(19);
  wr_u32(&s, uint32_t(kBodyOff + 9));
  s.push_back(char(kVersion));
  wr_u32(&s, seq);
  s.push_back(char(RESP_DECISION));
  s.push_back(granted ? 1 : 0);
  wr_f64(&s, remaining);
  return s;
}

std::string encode_empty(uint32_t seq) {
  std::string s;
  s.reserve(10);
  wr_u32(&s, uint32_t(kBodyOff));
  s.push_back(char(kVersion));
  wr_u32(&s, seq);
  s.push_back(char(RESP_EMPTY));
  return s;
}

std::string encode_error(uint32_t seq, const char* msg) {
  uint16_t mlen = uint16_t(std::strlen(msg));
  std::string s;
  wr_u32(&s, uint32_t(kBodyOff + 2 + mlen));
  s.push_back(char(kVersion));
  wr_u32(&s, seq);
  s.push_back(char(RESP_ERROR));
  s.append(reinterpret_cast<const char*>(&mlen), 2);
  s.append(msg, mlen);
  return s;
}

struct Item {
  uint64_t conn_id;
  uint32_t seq;
  uint8_t op;
  int32_t count;
  double a, b;
  std::string key;
  uint64_t t_ns;  // arrival (frame fully parsed) — serving latency start
  // Trace context (all zero when the frame carried no tail). tr_flags
  // bit 0 = traced-present, bit 1 = wire sampled flag — the layout
  // fe_batch_traces hands to Python.
  uint64_t tr_hi = 0, tr_lo = 0, tr_parent = 0;
  uint8_t tr_flags = 0;
};

struct Batch {
  int64_t id;
  std::vector<Item> items;
  uint64_t t_flush_ns = 0;  // batch cut — per-stage decomposition anchor
};

struct Passthrough {
  uint64_t conn_id;
  std::string frame;  // full body: [ver][seq][op][payload]
};

// One OP_ACQUIRE_MANY frame whose residue rows (cold keys, windows,
// probes — everything tier-0 could not decide) are out with Python.
// The reply is one RESP_BULK covering ALL rows: the C-decided verdicts
// wait here until fe_bulk_complete merges the residue verdicts in, so
// nothing is sent early and the frame stays all-or-one-reply. blob/
// offsets/counts are address-stable until the job is erased — the
// zero-copy contract fe_bulk_ptrs hands to Python.
struct BulkJob {
  int64_t id = 0;
  uint64_t conn_id = 0;
  uint32_t seq = 0;
  uint8_t flags = 0;  // the frame's wire flags byte
  uint8_t kind = 0;   // BULK_KIND_*
  bool with_remaining = false;
  double a = 0.0, b = 0.0;
  uint32_t n = 0;
  std::string blob;              // concatenated key bytes
  std::vector<int64_t> offsets;  // n + 1 boundaries into blob
  std::vector<int64_t> counts;   // per-row requested permits
  std::vector<uint8_t> verdict;  // 0 deny, 1 grant, 2 awaiting residue
  std::vector<float> remaining;  // per-row estimate (RESP_BULK is f32)
  std::vector<int32_t> residue;  // row indices Python must decide
  uint64_t t_ns = 0;             // arrival — serving latency start
  uint64_t tr_hi = 0, tr_lo = 0, tr_parent = 0;
  uint8_t tr_flags = 0;
};

// Per-frame hot-key aggregation slot (bulk_hot_feed scratch).
struct HotSlot {
  uint64_t hash = 0;
  uint64_t epoch = 0;
  int64_t row = 0;
  double weight = 0.0;
};

// One traced C-local decision, exported to Python as six u64s:
// hi, lo, parent, start_ns (CLOCK_MONOTONIC — the same epoch Python's
// perf_counter reads), dur_ns, meta (bits 0-7 wire flags, bit 8
// granted, bits 16-23 op).
struct TraceRec {
  uint64_t hi, lo, parent, start_ns, dur_ns, meta;
};

struct Conn {
  int fd = -1;
  uint64_t id = 0;
  bool authed = false;
  bool auth_pending = false;  // HELLO handed to Python, not yet resolved
  bool closing = false;     // close after outbox drains
  std::vector<uint8_t> in;  // accumulated unread bytes
  size_t in_off = 0;        // parse cursor into `in`
  std::vector<std::string> held;  // frames pipelined behind the HELLO
  size_t held_bytes = 0;
  std::string out;          // unwritten reply bytes
  size_t out_off = 0;       // write cursor into `out` (no O(n^2) erase)
  bool want_write = false;  // EPOLLOUT armed
  // Native bulk lane ordering: the last bulk frame's inflight job id
  // (0 once it completed). A chained chunk (wire _FLAG_CHAINED) must
  // decide AFTER its predecessor — the asyncio server's per-connection
  // bulk_tail contract — so chained frames park here until the
  // predecessor's reply is encoded.
  int64_t cur_bulk = 0;
  std::deque<std::string> parked_bulk;  // raw frame bodies, FIFO
  size_t parked_bytes = 0;
  // True when the connection's LAST bulk frame was handed to the
  // Python passthrough lane (malformed shape, or the lane disabled):
  // a chained successor must order behind it THERE (the server's
  // _bulk_tails), not race it natively — the asyncio server answers
  // a malformed chunk's error before its chained successor's reply,
  // and reply-for-reply parity includes that order.
  bool bulk_pt_tail = false;
  // io_uring transport state (round 16). Epoll connections leave these
  // idle. `wbuf` holds the bytes an in-flight SEND sqe points at — the
  // kernel reads them asynchronously, so they must not move while the
  // op is pending (c->out keeps accumulating and swaps in when the
  // current send drains). `uring_ops` counts CQEs still owed to this
  // connection; teardown parks the Conn in Shard::dying until it hits
  // zero — freeing wbuf under an in-flight SEND hands the kernel a
  // dangling iov.
  std::string wbuf;
  size_t wbuf_off = 0;
  uint32_t uring_ops = 0;
  bool recv_armed = false;    // multishot RECV in flight
  bool send_inflight = false;
  bool close_linked = false;  // linked SEND->CLOSE chain in flight
  bool dead = false;          // torn down; parked in Shard::dying
};

// Bound on bytes a connection may pipeline behind an unresolved HELLO.
constexpr size_t kMaxHeld = 256u << 10;

// ---------------------------------------------------------------------
// Tier-0 admission cache: a bounded per-key replica of the store's view,
// serving ACQUIRE permits/denies locally whenever the replica shows
// confident headroom against its last-synced value — the approximate
// local-decision/async-sync split (models/approximate.py) re-hosted
// BELOW the wire, so a hot key's decision never leaves this file. The
// Python sync pump drains each replica's accumulated local grants into
// one bulk saturating-debit launch (store.debit_many — sync_batch's
// decaying-counter semantic mirrored onto the bucket table, where
// score == capacity − tokens), pulls back fresh balances, and acks them
// here; budgets shrink/widen with the observed balance, so
// over-admission is bounded by the documented epsilon
// (2·budget + fill_rate·sync_interval, models/approximate.py
// overadmit_epsilon). Policy formula mirrored from
// models/approximate.py::headroom_budget — keep the two in sync.
// ---------------------------------------------------------------------

struct T0Entry {
  std::string key;
  double cap = 0.0, rate = 0.0;   // config identity ((a, b) of the frames)
  double last_remaining = 0.0;    // last authoritative balance (acked)
  double admitted = 0.0;          // local grants since the last ack
  double pending = 0.0;           // local grants not yet harvested
  double budget = 0.0;            // confident local admission headroom
  uint64_t last_ack_ns = 0;       // staleness anchor
  uint64_t last_touch_ns = 0;     // TTL anchor
  bool live = false;
};

struct T0Config {
  size_t mask = 0;                // per-slice slots - 1 (power of two)
  double split = 1.0;             // shard count: per-shard budget divisor
  double fraction = 0.5;          // budget = floor(balance * fraction)
  double min_budget = 64.0;       // below this, not worth hosting locally
  double max_budget = 1048576.0;
  uint64_t stale_ns = 0;          // max decision age since last ack
  uint64_t ttl_ns = 0;            // idle eviction
};

// Tier-0 partition lock: a TTAS spinlock, not a pthread mutex. The
// partition critical sections are sub-microsecond (a probe plus a few
// arithmetic ops; one aggregate update per key per bulk frame), and
// with N shard threads crossing partitions every frame the futex
// block/wake syscalls of a contended pthread mutex cost more than the
// work they guard (measured ~20% of 4-shard throughput). Spinners
// pause, then yield after a bound — the sync pump can hold a
// partition for tens of microseconds while harvesting, and a
// preempted holder must not burn the shard CPUs. Acquire/release
// atomics keep TSan's happens-before modeling exact.
class T0SpinMutex {
 public:
  void lock() {
    int spins = 0;
    while (flag_.exchange(1, std::memory_order_acquire) != 0) {
      do {
        if (++spins > 2048) {
          sched_yield();
          spins = 0;
        }
#if defined(__x86_64__)
        __builtin_ia32_pause();
#endif
      } while (flag_.load(std::memory_order_relaxed) != 0);
    }
  }
  void unlock() { flag_.store(0, std::memory_order_release); }
  bool try_lock() {
    return flag_.exchange(1, std::memory_order_acquire) == 0;
  }

 private:
  std::atomic<int> flag_{0};
};

// One SHARD's tier-0 replica slice (round 11: the multi-shard
// front-end). Each shard hosts its own replicas of the keys it serves
// and decides them against a budget DIVIDED by the shard count —
// t0_budget_of clamps to max_budget first and divides after, so the
// summed headroom across shards for any key never exceeds the flat
// single-shard budget: Σ_s floor(min(fraction·avail_s, max_budget)/N)
// ≤ min(fraction·avail, max_budget). One envelope, one epsilon — the
// same overadmit_epsilon(budget, fill, sync) bound as single-shard
// (docs/DESIGN.md §16 carries the inequality). The alternative — one
// replica per key in a key-hash-partitioned shared table — was built
// first and REJECTED on measurement: every frame then writes every hot
// key's entry from every shard, and the cross-core line transfers plus
// partition-lock handoffs cost ~25% of 4-shard throughput; per-shard
// slices make the hot path touch exclusively shard-local memory, which
// is where the node-level scaling actually comes from. The slice lock
// is only ever contended by the ONE sync pump's harvest/ack/retire
// (brief, ~100 Hz), never by another shard. Lock order: shard
// connection mutex → slice mutex; the sync pump takes slice mutexes
// only. (drl-verify extracts this order as the c:FeMutex →
// c:T0SpinMutex graph edge — by guard TYPE, so renaming variables
// cannot blind it — and fails on any cycle against it.)
struct T0Part {
  T0SpinMutex mu;
  T0Config cfg;               // per-partition copy, read/written under mu
  std::vector<T0Entry> tab;
  size_t scan = 0;            // harvest resume cursor (fairness)
  int64_t hits = 0;           // local grants
  int64_t local_denies = 0;   // confident local denies
  int64_t misses = 0;         // eligible requests that fell through
  int64_t installs = 0;
  int64_t evictions = 0;
  // Round 18 (conservation audit plane): cumulative TOKENS granted
  // locally by this slice — the ε-consumption the sync pump will later
  // reconcile, witnessed at the grant site itself so the Python-side
  // conservation ledger can hold local admissions to the documented
  // epsilon budget without trusting any Python counter. Monotonic;
  // read via fe_t0_eps.
  double grant_tokens = 0.0;
};

// Linear-probe window and the key-size cap that bounds table memory
// (slots × (entry + key) — ~1.5 MB at the 4096-slot default).
constexpr size_t kT0Probe = 8;
constexpr size_t kT0MaxKey = 256;

// Shard-count ceiling (fe_start_sharded clamps to it): bounds the
// per-frame touched[] scratch below, and a node with more epoll
// shards than this has no cores to feed them anyway.
constexpr int kMaxShards = 128;

uint64_t t0_hash(std::string_view k) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a 64
  for (unsigned char ch : k) {
    h ^= ch;
    h *= 1099511628211ull;
  }
  return h;
}

double t0_budget_of(const T0Config& cfg, double avail) {
  double b = avail * cfg.fraction;
  if (b > cfg.max_budget) b = cfg.max_budget;
  // Multi-shard split AFTER the max_budget clamp: the per-shard shares
  // then sum to ≤ the flat single-shard budget whatever the balance,
  // which is the whole single-envelope invariant (see T0Part). The
  // min_budget gate applies to the POST-split share — a bucket whose
  // per-shard share is not worth hosting stays exact, so tier-0's
  // semantic invisibility to small buckets only widens with shards.
  b /= cfg.split;
  if (b < cfg.min_budget) return 0.0;
  return std::floor(b);
}

struct Frontend;

// Handle tags: every ABI entry takes a void* that is either the whole
// Frontend (aggregate view / shard 0 for per-shard calls — the
// single-shard compatibility posture a stale Python half relies on) or
// one Shard (returned by fe_shard). Both structs lead with a magic so
// the entry points can tell which they were handed.
// ---------------------------------------------------------------------
// io_uring data plane (round 16): the shard IO loop rebuilt on a raw-
// syscall, liburing-free ring — multishot accept, multishot recv over a
// provided-buffer ring, submit-on-reply SEND batching, linked
// SEND->CLOSE teardown, and an optional SQPOLL mode where a hot shard
// submits without any syscall at all. The reply bytes are the spec:
// everything from parse_frames down is shared with the epoll loop, only
// the transport differs, and every shard falls back to io_loop (with a
// recorded reason) when the kernel, seccomp, or an env override refuses.
//
// The UAPI structs and constants are defined here rather than pulled
// from <linux/io_uring.h>: the build host's header may predate the
// 5.19 features this transport needs (multishot recv, PBUF_RING) even
// when the running kernel has them, and the ABI below is frozen by the
// kernel's compatibility contract.
// ---------------------------------------------------------------------

constexpr long kSysUringSetup = 425;
constexpr long kSysUringEnter = 426;
constexpr long kSysUringRegister = 427;

struct DrlSqe {  // struct io_uring_sqe (64 bytes, ABI-frozen)
  uint8_t opcode;
  uint8_t flags;    // IOSQE_* bits
  uint16_t ioprio;  // multishot flags live here for accept/recv
  int32_t fd;
  uint64_t off;
  uint64_t addr;
  uint32_t len;
  uint32_t op_flags;  // msg_flags / accept_flags / cancel_flags union
  uint64_t user_data;
  uint16_t buf_group;  // buf_index/buf_group union
  uint16_t personality;
  int32_t splice_fd_in;
  uint64_t pad2[2];
};
static_assert(sizeof(DrlSqe) == 64, "io_uring_sqe ABI");

struct DrlCqe {  // struct io_uring_cqe (16 bytes)
  uint64_t user_data;
  int32_t res;
  uint32_t flags;
};
static_assert(sizeof(DrlCqe) == 16, "io_uring_cqe ABI");

struct DrlSqOffsets {
  uint32_t head, tail, ring_mask, ring_entries, flags, dropped, array,
      resv1;
  uint64_t resv2;
};
struct DrlCqOffsets {
  uint32_t head, tail, ring_mask, ring_entries, overflow, cqes, flags,
      resv1;
  uint64_t resv2;
};
struct DrlUringParams {  // struct io_uring_params (120 bytes)
  uint32_t sq_entries, cq_entries, flags, sq_thread_cpu, sq_thread_idle,
      features, wq_fd, resv[3];
  DrlSqOffsets sq_off;
  DrlCqOffsets cq_off;
};
static_assert(sizeof(DrlUringParams) == 120, "io_uring_params ABI");

constexpr uint64_t kUringOffSqRing = 0;
constexpr uint64_t kUringOffCqRing = 0x8000000ull;
constexpr uint64_t kUringOffSqes = 0x10000000ull;

constexpr uint32_t kUringFeatSingleMmap = 1u << 0;
constexpr uint32_t kUringSetupSqpoll = 1u << 1;
constexpr uint32_t kUringSetupCqsize = 1u << 3;
constexpr uint32_t kUringSetupClamp = 1u << 4;

constexpr uint32_t kUringSqNeedWakeup = 1u << 0;  // sq ring flags word
constexpr uint32_t kUringEnterGetevents = 1u << 0;
constexpr uint32_t kUringEnterSqWakeup = 1u << 1;

constexpr uint8_t kOpTimeout = 11;      // IORING_OP_TIMEOUT
constexpr uint8_t kOpAccept = 13;       // IORING_OP_ACCEPT
constexpr uint8_t kOpAsyncCancel = 14;  // IORING_OP_ASYNC_CANCEL
constexpr uint8_t kOpClose = 19;        // IORING_OP_CLOSE
constexpr uint8_t kOpRead = 22;         // IORING_OP_READ
constexpr uint8_t kOpSend = 26;         // IORING_OP_SEND
constexpr uint8_t kOpRecv = 27;         // IORING_OP_RECV
// IORING_OP_SOCKET landed in 5.19 alongside multishot recv and
// PBUF_RING, which have no probe bit of their own — its presence in the
// opcode probe is the documented feature-level proxy.
constexpr uint8_t kOpSocketProxy = 45;

constexpr uint16_t kAcceptMultishot = 1u << 0;  // sqe->ioprio, accept
constexpr uint16_t kRecvMultishot = 1u << 1;    // sqe->ioprio, recv
constexpr uint8_t kSqeFixedFile = 1u << 0;
constexpr uint8_t kSqeIoLink = 1u << 2;
constexpr uint8_t kSqeBufferSelect = 1u << 5;

constexpr uint32_t kCqeFBuffer = 1u << 0;  // upper 16 bits carry the bid
constexpr uint32_t kCqeFMore = 1u << 1;    // multishot op still armed
constexpr uint32_t kCqeBufferShift = 16;

constexpr unsigned kRegRegisterFiles = 2;
constexpr unsigned kRegRegisterProbe = 8;
constexpr unsigned kRegRegisterPbufRing = 22;

struct DrlProbeOp {
  uint8_t op;
  uint8_t resv;
  uint16_t flags;  // bit 0 = IO_URING_OP_SUPPORTED
  uint32_t resv2;
};
struct DrlProbe {
  uint8_t last_op;
  uint8_t ops_len;
  uint16_t resv;
  uint32_t resv2[3];
  DrlProbeOp ops[48];
};

struct DrlKTimespec {  // struct __kernel_timespec (TIMEOUT ops)
  int64_t tv_sec;
  long long tv_nsec;
};

struct DrlBufReg {  // struct io_uring_buf_reg
  uint64_t ring_addr;
  uint32_t ring_entries;
  uint16_t bgid;
  uint16_t flags;
  uint64_t resv[3];
};
struct DrlBuf {  // struct io_uring_buf; entry 0's resv overlays the
  uint64_t addr;  // ring tail the producer publishes
  uint32_t len;
  uint16_t bid;
  uint16_t resv;
};

inline int sys_uring_setup(unsigned entries, DrlUringParams* p) {
  return int(syscall(kSysUringSetup, entries, p));
}
inline int sys_uring_enter(int fd, unsigned to_submit, unsigned min_c,
                           unsigned flags) {
  return int(syscall(kSysUringEnter, fd, to_submit, min_c, flags,
                     nullptr, size_t(0)));
}
inline int sys_uring_register(int fd, unsigned opcode, void* arg,
                              unsigned nr) {
  return int(syscall(kSysUringRegister, fd, opcode, arg, nr));
}

// Transport mode knob (fe_start_sharded2's uring_mode; mirrored as
// URING_OFF/URING_ON/URING_SQPOLL in utils/native.py — drl-check's
// transport-flag rule pins the pair, so a drift here is a build break,
// not a silent mode swap).
constexpr int kUringOff = 0;
constexpr int kUringOn = 1;
constexpr int kUringSqpoll = 2;

constexpr unsigned kUringSqEntries = 256;
constexpr unsigned kUringCqEntries = 4096;
constexpr unsigned kUringBufCount = 64;      // provided-buffer slots
constexpr size_t kUringBufSize = 32u << 10;  // 32 KiB per slot
constexpr uint16_t kUringBgid = 7;           // buffer-group id

// user_data = (kind << 56) | conn_id. Tags 0-2 stay reserved for the
// listen/eventfd/timerfd fixed-file slots like the epoll loop's epoll
// tags, so conn ids never collide with control ops.
constexpr uint64_t kUdAccept = 1;
constexpr uint64_t kUdEvRead = 2;
constexpr uint64_t kUdTfRead = 3;
constexpr uint64_t kUdRecv = 4;
constexpr uint64_t kUdSend = 5;
constexpr uint64_t kUdClose = 6;
constexpr uint64_t kUdCancel = 7;

inline uint64_t uring_ud(uint64_t kind, uint64_t id) {
  return (kind << 56) | id;
}

// Per-shard ring state. Conn sockets are deliberately NOT in the
// registered-file table: fixed slots are reused the moment a table
// entry is overwritten, and a slot recycled while a canceled op is
// still in flight attributes the completion to the WRONG connection —
// the registered table holds only the three immortal control fds
// (listen=0, eventfd=1, timerfd=2). docs/DESIGN.md §21.
struct UringRing {
  int fd = -1;
  bool sqpoll = false;
  void* sq_map = nullptr;
  size_t sq_map_len = 0;
  void* cq_map = nullptr;  // == sq_map under FEAT_SINGLE_MMAP
  size_t cq_map_len = 0;
  DrlSqe* sqes = nullptr;
  size_t sqes_len = 0;
  std::atomic<uint32_t>* sq_head = nullptr;  // kernel-consumed cursor
  std::atomic<uint32_t>* sq_tail = nullptr;
  uint32_t sq_mask = 0;
  uint32_t* sq_array = nullptr;
  std::atomic<uint32_t>* sq_flags = nullptr;  // NEED_WAKEUP under SQPOLL
  std::atomic<uint32_t>* cq_head = nullptr;
  std::atomic<uint32_t>* cq_tail = nullptr;
  uint32_t cq_mask = 0;
  DrlCqe* cqes = nullptr;
  // Provided-buffer ring (bgid kUringBgid) feeding multishot recv.
  DrlBuf* buf_ring = nullptr;
  size_t buf_ring_len = 0;
  uint8_t* buf_pool = nullptr;
  size_t buf_pool_len = 0;
  uint16_t buf_tail = 0;
  uint32_t sq_pending = 0;  // SQEs staged since the last submit
  uint64_t ev_buf = 0;      // READ landing pad, eventfd slot
  uint64_t tf_buf = 0;      // READ landing pad, timerfd slot
  // Telemetry (fe_uring_counts): enter calls are made both under the
  // shard mutex (submits) and outside it (the wait leg), so atomics.
  std::atomic<long long> enters{0};
  std::atomic<long long> sqes_submitted{0};
  std::atomic<long long> cqes_seen{0};
};

constexpr uint32_t kFeMagic = 0xFE11D311u;
constexpr uint32_t kShardMagic = 0x5AAD0011u;

// One epoll serving shard (round 11): its own SO_REUSEPORT listener on
// the shared port (kernel-level accept balancing — no dispatch thread),
// its own IO thread, connection table, micro-batch queues, bulk lane,
// stats, and rings, all under its own mutex. A connection lives its
// whole life on one shard, so the per-connection order contract and the
// chained-chunk parking (round 8) carry over shard-locally, unchanged.
// The hot path touches NO cross-shard state: tier-0 decisions draw
// from the shard's own replica slice (see T0Part above), and only the
// sync pump's harvest/ack/retire ever crosses shards.
struct Shard {
  uint32_t magic = kShardMagic;
  Frontend* owner = nullptr;
  int index = 0;
  int listen_fd = -1, epfd = -1, evfd = -1, tfd = -1;
  // Read-only copies of the Frontend-level serving knobs (stamped
  // before the IO thread starts) so the hot path never reaches across.
  size_t max_batch = 4096;
  uint64_t deadline_ns = 300000;
  bool require_auth = false;
  std::thread io;

  // io_uring transport (round 16): non-null ring means this shard's IO
  // thread runs uring_loop; the eventfd/timerfd above double as
  // registered-file slots so arm_deadline/wake_io stay transport-
  // neutral. uring_reason records why a shard that was ASKED for uring
  // fell back to epoll (fe_uring_reason / OPERATIONS.md §17).
  UringRing* ring = nullptr;
  bool uring = false;
  bool uring_sqpoll = false;
  bool uring_sweep = false;  // a conn needs re-arm/reap at burst end
  bool tfd_armed = false;    // skip redundant timerfd disarm syscalls
  std::string uring_reason;
  // Connections torn down but still owed CQEs (in-flight SEND/RECV/
  // CANCEL): reaped when their uring_ops drain to zero.
  std::unordered_map<uint64_t, Conn*> dying;
  // Data-plane syscalls this shard has issued (both transports count
  // every epoll_wait/accept/recv/send/epoll_ctl/timerfd/eventfd/enter
  // call) — the syscalls/frame evidence column is this over
  // requests_served, measured, not modeled.
  std::atomic<long long> io_syscalls{0};

  FeMutex mu;
  FeCondVar cv;
  std::unordered_map<uint64_t, Conn*> conns;
  uint64_t next_conn_id = 16;  // tags 0-2 are listen/eventfd/timerfd
  std::vector<Item> pending;
  uint64_t pending_oldest_ns = 0;
  std::deque<Batch> ready;
  std::deque<Passthrough> pt;
  std::unordered_map<int64_t, Batch> inflight;  // handed to Python
  int64_t next_batch_id = 1;
  bool pump_waiting = false;
  int64_t cur_batch_id = 0;  // last batch returned by fe_wait
  Passthrough cur_pt;

  int64_t requests_served = 0;
  int64_t connections_served = 0;
  int64_t batches_flushed = 0;
  uint64_t hist[kHistBuckets] = {0};
  int64_t hist_total = 0;
  double hist_sum = 0.0;
  // Per-stage decomposition of the serving span (same bucket convention):
  // stage 0 = queue (frame parsed -> batch cut), stage 1 = exec (batch
  // cut -> fe_complete/fe_fail, i.e. Python dispatch + store + kernel).
  // serving ~= queue + exec + reply-write; exported via fe_stage_hist.
  static constexpr int kStages = 2;
  uint64_t stage_hist[kStages][kHistBuckets] = {{0}};
  int64_t stage_total[kStages] = {0};
  double stage_sum[kStages] = {0.0};

  // Completed-span records for traced requests decided entirely in C
  // (tier-0 local grant/deny): Python's sync pump harvests these via
  // fe_trace_harvest and emits them as spans, so locally-granted
  // requests still leave a trace. Bounded; overflow drops oldest.
  std::deque<TraceRec> trace_ring;
  int64_t trace_dropped = 0;

  // Native bulk lane (fe_bulk_configure; round 8). Off by default so a
  // freshly-built .so under an older Python half keeps the round-7
  // passthrough behavior — the pump arms it only when it binds the
  // fe_bulk_* ABI.
  bool bulk_native = false;  // parse + decide OP_ACQUIRE_MANY here
  bool bulk_t0 = true;       // per-row tier-0 decisions on bulk rows
  bool bulk_hot = false;     // per-frame top-K feed for the sketch
  std::deque<int64_t> bulk_ready;
  std::unordered_map<int64_t, BulkJob> bulk_inflight;
  int64_t next_bulk_id = 1;
  int64_t cur_bulk_id = 0;  // last job returned by fe_wait
  int64_t bulk_frames = 0;
  int64_t bulk_frames_local = 0;  // answered without leaving C
  int64_t bulk_rows = 0;
  int64_t bulk_rows_local = 0;    // tier-0 grant/deny rows
  int64_t bulk_rows_residue = 0;  // rows that crossed into Python
  double bulk_permits_local = 0.0;  // locally granted permits — the
                                    // amount the sync pump will debit
  // Bulk parse scratch, reused per frame under mu (no per-frame allocs
  // in the steady state; a residue job copies them out).
  std::vector<int64_t> bulk_offsets_scratch;
  std::vector<int64_t> bulk_counts_scratch;
  std::vector<uint8_t> bulk_verdict_scratch;
  std::vector<float> bulk_rem_scratch;
  std::vector<int32_t> bulk_residue_scratch;
  // Round 11: per-frame key aggregation for the tier-0 decide pass. A
  // hot frame carries thousands of rows over a few dozen keys; the
  // parse pass groups them (open-addressed, epoch-stamped table) so
  // the decide pass takes each touched partition's lock ONCE per frame
  // and makes ONE envelope decision per (key, summed count) — per-row
  // locking across N shard threads cache-bounces the partition mutexes
  // (measured SLOWER at 4 shards than one), and even batched per-row
  // decides keep the lock held for the whole row scan. Keys whose
  // aggregate does not cleanly fit the budget fall back to the exact
  // per-row legacy walk under the same lock (the boundary minority),
  // so observable semantics are unchanged.
  std::vector<int32_t> bulk_aggof_scratch;    // row -> agg index | -1
  std::vector<uint64_t> bulk_aggtab_epoch;    // open table stamp
  std::vector<int32_t> bulk_aggtab_idx;       // open table payload
  uint64_t bulk_agg_epoch = 0;
  std::vector<uint64_t> agg_hash;
  std::vector<int32_t> agg_first;   // first row (key-byte authority)
  std::vector<int32_t> agg_nrows;
  std::vector<int64_t> agg_total;   // summed requested permits
  std::vector<uint8_t> agg_mode;    // see kAgg* in handle_bulk_frame
  std::vector<double> agg_before;   // admitted before a grant-all
  std::vector<double> agg_lastrem;  // last acked balance snapshot
  std::vector<double> agg_run;      // per-row remaining fill cursor
  // Hot-key feed for the heavy-hitter sketch: per-frame open-addressed
  // aggregation scratch + the bounded harvest ring fe_hot_harvest
  // drains (overflow drops oldest — telemetry, not accounting).
  std::vector<HotSlot> hot_scratch;
  uint64_t hot_epoch = 0;
  std::deque<std::pair<std::string, double>> hot_ring;
  int64_t hot_dropped = 0;
};

// The whole front-end: N shards accepting on SO_REUSEPORT listeners
// bound to ONE port, plus the key-hash-partitioned tier-0 replica
// table they all decide against. The Python half runs one pump thread
// per shard (fe_shard hands out the per-shard handles) and ONE sync
// pump that drains every partition's grant ledger through the
// Frontend-level harvest/ack/retire calls — a single reconciliation
// stream into the store, a single epsilon envelope across shards.
struct Frontend {
  uint32_t magic = kFeMagic;
  int port = 0;
  int nshards = 1;
  int uring_mode = kUringOff;  // requested transport (kUring*)
  size_t max_batch = 4096;
  uint64_t deadline_ns = 300000;
  bool require_auth = false;
  std::atomic<bool> stopping{false};
  std::vector<Shard*> shards;
  // Tier-0 partitions, one per shard by key-hash affinity (see T0Part).
  // Empty tables until fe_t0_configure; t0_enabled is the lock-free
  // fast gate the parse loops read before paying a partition lock.
  std::vector<T0Part*> t0parts;
  std::atomic<bool> t0_enabled{false};
  // Harvest fan-out cursor: which partition the Frontend-level harvest
  // resumes from (single sync-pump caller; rotates so an overflowing
  // round cannot starve the high-numbered partitions).
  size_t harvest_part = 0;
  // Same rotation for the shard-level trace/hot harvests.
  size_t trace_shard = 0;
  size_t hot_shard = 0;
};

inline Frontend* as_frontend(void* h) {
  return *static_cast<uint32_t*>(h) == kFeMagic
             ? static_cast<Frontend*>(h)
             : nullptr;
}

// Per-shard entry points accept either handle kind; a Frontend handle
// means shard 0 — exactly the single-shard ABI a stale Python half
// (which never calls fe_shard) keeps using.
inline Shard* shard_of(void* h) {
  Frontend* fe = as_frontend(h);
  return fe != nullptr ? fe->shards[0] : static_cast<Shard*>(h);
}

inline Frontend* owner_of(void* h) {
  Frontend* fe = as_frontend(h);
  return fe != nullptr ? fe : static_cast<Shard*>(h)->owner;
}

// Aggregating entry points: every shard for a Frontend handle, just the
// one for a Shard handle (the per-shard breakdown OP_STATS exposes).
inline std::vector<Shard*> shards_of(void* h) {
  Frontend* fe = as_frontend(h);
  if (fe != nullptr) return fe->shards;
  return {static_cast<Shard*>(h)};
}

// The shard's own tier-0 slice (nullptr before fe_t0_configure).
inline T0Part* t0_slice(Shard* sh);

// Slices a tier-0 call touches: the shard's own for a Shard handle
// (per-shard breakdown / the hot path), all of them for a Frontend
// handle (the sync pump's merge view).
inline std::vector<T0Part*> t0parts_of(void* h) {
  Frontend* fe = as_frontend(h);
  if (fe != nullptr) return fe->t0parts;
  Shard* sh = static_cast<Shard*>(h);
  if (sh->owner->t0parts.empty()) return {};
  return {sh->owner->t0parts[size_t(sh->index)]};
}

inline T0Part* t0_slice(Shard* sh) {
  Frontend* fe = sh->owner;
  return fe->t0parts.empty() ? nullptr
                             : fe->t0parts[size_t(sh->index)];
}

constexpr size_t kTraceRing = 1024;

void trace_ring_push_raw(Shard* sh, uint64_t hi, uint64_t lo,
                         uint64_t parent, uint8_t tr_flags, uint8_t op,
                         bool granted, uint64_t start_ns,
                         uint64_t end_ns) {
  // mu held.
  if (sh->trace_ring.size() >= kTraceRing) {
    sh->trace_ring.pop_front();
    sh->trace_dropped++;
  }
  TraceRec r;
  r.hi = hi;
  r.lo = lo;
  r.parent = parent;
  r.start_ns = start_ns;
  r.dur_ns = end_ns - start_ns;
  r.meta = uint64_t(tr_flags) | (granted ? 0x100u : 0u) |
           (uint64_t(op) << 16);
  sh->trace_ring.push_back(r);
}

void trace_ring_push(Shard* sh, const Item& it, bool granted,
                     uint64_t end_ns) {
  trace_ring_push_raw(sh, it.tr_hi, it.tr_lo, it.tr_parent, it.tr_flags,
                      it.op, granted, it.t_ns, end_ns);
}

T0Entry* t0_find(T0Part* part, std::string_view key, uint64_t h,
                 double cap, double rate) {
  // part->mu held.
  if (part->tab.empty()) return nullptr;
  size_t idx = size_t(h) & part->cfg.mask;
  for (size_t p = 0; p < kT0Probe; p++) {
    T0Entry& e = part->tab[(idx + p) & part->cfg.mask];
    if (e.live && e.cap == cap && e.rate == rate &&
        std::string_view(e.key) == key) {
      return &e;
    }
  }
  return nullptr;
}

void t0_install(T0Part* part, const std::string& key, double cap,
                double rate, double remaining, uint64_t now,
                double cost) {
  // Called with the deciding shard's connection mutex held; takes the
  // shard's OWN slice mutex (lock order: shard mu → slice mu — the one
  // nesting this file allows). Seed/refresh a replica from an
  // authoritative device decision (fe_complete). A refresh keeps
  // `admitted`: the device balance predates our un-drained local
  // grants, so the envelope stays conservative until the next sync
  // acks them away.
  //
  // `cost` is the granting request's token count: a fresh install must
  // have the headroom to decide at least ONE request of the cost that
  // seeded it — min_budget alone is denominated for unit permits, and
  // a replica whose budget cannot cover the workload's typical cost
  // can never grant locally (every request would miss), so installing
  // it only burns probe-window slots the genuinely decidable keys
  // need. Token-denominated install terms, not request-denominated
  // (the count>1 audit, ISSUE 10 satellite).
  if (key.size() > kT0MaxKey || part == nullptr) return;
  if (cost < 1.0) cost = 1.0;  // probe-seeded installs size for 1 token
  uint64_t h = t0_hash(key);
  std::lock_guard<T0SpinMutex> lk(part->mu);
  if (part->tab.empty()) return;
  T0Entry* e = t0_find(part, key, h, cap, rate);
  if (e == nullptr) {
    double budget = t0_budget_of(part->cfg, remaining);
    if (budget <= 0.0 || budget < cost) {
      return;  // headroom too small to host locally
    }
    size_t idx = size_t(h) & part->cfg.mask;
    for (size_t p = 0; p < kT0Probe && e == nullptr; p++) {
      T0Entry& cand = part->tab[(idx + p) & part->cfg.mask];
      if (!cand.live) {
        e = &cand;
      } else if (cand.pending == 0.0 &&
                 now - cand.last_touch_ns > part->cfg.ttl_ns) {
        part->evictions++;  // reuse an idle slot (un-drained grants pin)
        e = &cand;
      }
    }
    if (e == nullptr) return;  // probe window live: bounded table, skip
    e->key = key;
    e->cap = cap;
    e->rate = rate;
    e->admitted = 0.0;
    e->pending = 0.0;
    e->live = true;
    e->last_remaining = remaining;
    e->budget = budget;
    e->last_ack_ns = now;
    e->last_touch_ns = now;
    part->installs++;
    return;
  }
  e->last_remaining = remaining;
  e->budget = t0_budget_of(part->cfg,
                           std::max(remaining - e->admitted, 0.0));
  e->last_ack_ns = now;
  e->last_touch_ns = now;
}

int t0_decide_locked(T0Part* part, std::string_view key, uint64_t h,
                     int64_t count, double cap, double rate,
                     double* rem_out, uint64_t now) {
  // part->mu held. 1 = grant locally, 0 = deny locally, -1 = fall
  // through to the device path. The estimate reported with local
  // replies is the envelope's own conservative view (last acked
  // balance minus local grants — refill since the ack is credit the
  // next sync will restore). `now` comes from the caller: the bulk
  // lane decides up to ~100K rows per frame and must not pay one
  // clock read per row.
  T0Entry* e = t0_find(part, key, h, cap, rate);
  if (e == nullptr) {
    part->misses++;
    return -1;
  }
  if (now - e->last_ack_ns > part->cfg.stale_ns) {
    part->misses++;  // envelope too old: device decides (and re-seeds)
    return -1;
  }
  e->last_touch_ns = now;
  double cnt = double(count);
  if (e->admitted + cnt <= e->budget) {
    e->admitted += cnt;
    e->pending += cnt;
    part->hits++;
    part->grant_tokens += cnt;
    *rem_out = std::max(e->last_remaining - e->admitted, 0.0);
    return 1;
  }
  // Confident deny: even crediting FULL refill since the last ack, the
  // last-synced balance cannot cover this request — uncertainty falls
  // through instead of guessing.
  double elapsed_s = double(now - e->last_ack_ns) * 1e-9;
  double optimistic = e->last_remaining - e->admitted + rate * elapsed_s;
  if (optimistic < cnt) {
    part->local_denies++;
    *rem_out = std::max(e->last_remaining - e->admitted, 0.0);
    return 0;
  }
  part->misses++;
  return -1;
}

int t0_decide(T0Part* part, std::string_view key, int64_t count,
              double cap, double rate, double* rem_out, uint64_t now) {
  // Scalar-lane entry: called with the deciding shard's connection
  // mutex held; takes the shard's OWN slice mutex (the shard's budget
  // share is its to draw down — the split in t0_budget_of keeps the
  // cross-shard sum inside the flat envelope). The bulk lane does NOT
  // come through here: it aggregates a frame by key and locks the
  // slice once (handle_bulk_frame).
  if (part == nullptr) return -1;
  uint64_t h = t0_hash(key);
  std::lock_guard<T0SpinMutex> lk(part->mu);
  return t0_decide_locked(part, key, h, count, cap, rate, rem_out, now);
}

int hist_bucket(double seconds) {
  int idx = 0;
  if (seconds > 1e-6) {
    idx = int(std::log(seconds / 1e-6) * kInvLogBase) + 1;
    if (idx > kHistBuckets - 1) idx = kHistBuckets - 1;
    if (idx < 0) idx = 0;
  }
  return idx;
}

void hist_record(Shard* sh, double seconds) {
  sh->hist[hist_bucket(seconds)]++;
  sh->hist_total++;
  sh->hist_sum += seconds;
}

void stage_record(Shard* sh, int stage, double seconds) {
  sh->stage_hist[stage][hist_bucket(seconds)]++;
  sh->stage_total[stage]++;
  sh->stage_sum[stage] += seconds;
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// Flush as much of conn->out as the socket accepts. mu held.
void flush_out(Shard* sh, Conn* c);

// io_uring transport entry points (defined after the epoll loop; the
// shared helpers below branch to them when the shard runs on the ring).
void uring_close_conn(Shard* sh, Conn* c);
void uring_arm_send(Shard* sh, Conn* c);
void uring_submit(Shard* sh);

// Data-plane syscall accounting (see Shard::io_syscalls).
inline void count_sys(Shard* sh, int n = 1) {
  sh->io_syscalls.fetch_add(n, std::memory_order_relaxed);
}

void close_conn(Shard* sh, Conn* c) {
  // mu held. Removes from epoll + conn map and frees.
  if (sh->uring) {
    uring_close_conn(sh, c);
    return;
  }
  count_sys(sh, 2);
  epoll_ctl(sh->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  ::close(c->fd);
  sh->conns.erase(c->id);
  delete c;
}

void send_to_conn(Shard* sh, Conn* c, const char* data, size_t len) {
  // mu held. Append-or-write: when nothing is queued, try the socket
  // immediately (saves an epoll round trip — the common case); queue
  // the remainder and arm EPOLLOUT on partial writes.
  if (c->closing || c->dead) return;
  if (sh->uring) {
    // uring lane: stage and arm a SEND op; the caller's burst-end
    // submit batches every staged reply into one (or zero, under
    // SQPOLL) enter call — the submit-on-reply contract.
    if (c->out.size() - c->out_off + len > kMaxConnOut) {
      c->closing = true;
      c->out.clear();
      c->out_off = 0;
      uring_arm_send(sh, c);
      return;
    }
    c->out.append(data, len);
    uring_arm_send(sh, c);
    return;
  }
  if (c->out.size() == c->out_off) {
    c->out.clear();
    c->out_off = 0;
    count_sys(sh);
    ssize_t n = ::send(c->fd, data, len, MSG_NOSIGNAL);
    if (n == ssize_t(len)) return;
    if (n < 0) {
      if (errno != EAGAIN && errno != EWOULDBLOCK) {
        c->closing = true;  // broken pipe: IO thread reaps on next event
        return;
      }
      n = 0;
    }
    data += n;
    len -= size_t(n);
  }
  if (c->out.size() - c->out_off + len > kMaxConnOut) {
    c->closing = true;  // unbounded outbox = dead/hostile reader
    c->out.clear();
    c->out_off = 0;
    return;
  }
  c->out.append(data, len);
  if (!c->want_write) {
    c->want_write = true;
    epoll_event ev{};
    ev.events = EPOLLIN | EPOLLOUT;
    ev.data.u64 = c->id;
    count_sys(sh);
    epoll_ctl(sh->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

void queue_to_conn(Conn* c, const char* data, size_t len) {
  // mu held. Append-only variant of send_to_conn for replies generated
  // inside a parse burst (tier-0 local decisions, PING): the caller
  // flushes ONCE per burst via flush_queued, collapsing per-reply
  // send() syscalls — at tier-0 rates the syscall per reply, not the
  // decision, is the serving ceiling.
  if (c->closing) return;
  if (c->out.size() - c->out_off + len > kMaxConnOut) {
    c->closing = true;  // unbounded outbox = dead/hostile reader
    c->out.clear();
    c->out_off = 0;
    return;
  }
  c->out.append(data, len);
}

void flush_queued(Shard* sh, Conn* c) {
  // mu held. Push burst-queued replies out with one send(); arm
  // EPOLLOUT for any leftover. Never closes/frees the connection (hard
  // errors mark `closing` and the IO loop reaps on the next event), so
  // callers keep their pointer.
  if (sh->uring) {
    uring_arm_send(sh, c);
    return;
  }
  if (c->out_off >= c->out.size() || c->want_write) return;
  count_sys(sh);
  ssize_t n = ::send(c->fd, c->out.data() + c->out_off,
                     c->out.size() - c->out_off, MSG_NOSIGNAL);
  if (n >= 0) {
    c->out_off += size_t(n);
  } else if (errno != EAGAIN && errno != EWOULDBLOCK) {
    c->closing = true;
    c->out.clear();
    c->out_off = 0;
    return;
  }
  if (c->out_off >= c->out.size()) {
    c->out.clear();
    c->out_off = 0;
    return;
  }
  c->want_write = true;
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLOUT;
  ev.data.u64 = c->id;
  count_sys(sh);
  epoll_ctl(sh->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void flush_out(Shard* sh, Conn* c) {
  // mu held. Cursor-based drain: erase-from-front per partial send is
  // O(n^2) memmove on a multi-MB backpressured outbox, all of it under
  // the global mutex — advance out_off instead, compact occasionally.
  if (sh->uring) {
    uring_arm_send(sh, c);
    return;
  }
  while (c->out_off < c->out.size()) {
    count_sys(sh);
    ssize_t n = ::send(c->fd, c->out.data() + c->out_off,
                       c->out.size() - c->out_off, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (c->out_off > (1u << 20)) {
          c->out.erase(0, c->out_off);
          c->out_off = 0;
        }
        return;
      }
      close_conn(sh, c);
      return;
    }
    c->out_off += size_t(n);
  }
  c->out.clear();
  c->out_off = 0;
  if (c->closing) {
    close_conn(sh, c);
    return;
  }
  if (c->want_write) {
    c->want_write = false;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = c->id;
    epoll_ctl(sh->epfd, EPOLL_CTL_MOD, c->fd, &ev);
  }
}

// A completed batch reopens the pipeline: hand the accumulated arrivals
// straight to the pump instead of waiting out the deadline timer (the
// adaptive half of flush-on-idle — batch size tracks Python's service
// time under load, and completion immediately restarts service).
void maybe_flush_after_complete(Shard* sh);

void flush_pending(Shard* sh, bool include_tail) {
  // mu held. pending -> ready queue in <= max_batch chunks (max_batch
  // bounds flush SIZE like the asyncio MicroBatcher's, not just the
  // flush trigger). A size-triggered flush (include_tail=false) emits
  // only FULL chunks and keeps the sub-max_batch tail pending to
  // coalesce with the next arrivals — the MicroBatcher's remainder
  // semantics (batcher.py); deadline/idle flushes drain everything
  // (the tail is as overdue as the rest).
  if (sh->pending.empty()) return;
  size_t n = sh->pending.size();
  size_t limit = include_tail ? n : (n / sh->max_batch) * sh->max_batch;
  if (limit == 0) return;
  size_t pos = 0;
  uint64_t t_cut = now_ns();
  while (pos < limit) {
    size_t take = limit - pos;
    if (take > sh->max_batch) take = sh->max_batch;
    Batch b;
    b.id = sh->next_batch_id++;
    b.t_flush_ns = t_cut;
    b.items.assign(std::make_move_iterator(sh->pending.begin() + pos),
                   std::make_move_iterator(sh->pending.begin() + pos +
                                           take));
    pos += take;
    sh->ready.push_back(std::move(b));
    sh->batches_flushed++;
  }
  if (limit == n) {
    sh->pending.clear();
  } else {
    sh->pending.erase(sh->pending.begin(),
                      sh->pending.begin() + static_cast<ptrdiff_t>(limit));
    sh->pending_oldest_ns = sh->pending.front().t_ns;
  }
  sh->cv.notify_one();
}

void maybe_flush_after_complete(Shard* sh) {
  // mu held (called from fe_complete / fe_fail / finish_bulk_job).
  if (!sh->pending.empty() && sh->ready.empty() && sh->pt.empty() &&
      sh->inflight.empty() && sh->bulk_ready.empty() &&
      sh->bulk_inflight.empty()) {
    flush_pending(sh, /*include_tail=*/true);  // pipeline idle: drain
  }
}

void to_passthrough(Shard* sh, Conn* c, const uint8_t* body,
                    size_t len) {
  // mu held. Hand a frame to Python wholesale — the wire module stays
  // the single authority for every non-hot (or malformed) shape.
  Passthrough ptf;
  ptf.conn_id = c->id;
  ptf.frame.assign(reinterpret_cast<const char*>(body), len);
  sh->pt.push_back(std::move(ptf));
  sh->cv.notify_one();
}

// ---------------------------------------------------------------------
// Native bulk lane (round 8). One OP_ACQUIRE_MANY frame = one RESP_BULK
// reply; tier-0 decides hot bucket rows per-row against the SAME
// replica table (and therefore the same epsilon envelope) as the scalar
// ACQUIRE lane — one budget, not two. Rows tier-0 cannot decide cross
// the fe_bulk_* ABI as a zero-copy residue batch.
// ---------------------------------------------------------------------

std::string encode_bulk_reply(uint32_t seq, bool with_rem, uint32_t n,
                              const uint8_t* verdict,
                              const float* remaining) {
  // Byte-identical to wire.encode_bulk_response: [u8 flags][u32 n]
  // [granted bits, LSB-first] [f32 remaining × n iff flags bit 0].
  size_t nbits = (size_t(n) + 7) / 8;
  size_t payload = kBulkRespHead + nbits + (with_rem ? 4ull * n : 0);
  std::string s;
  s.reserve(4 + kBodyOff + payload);
  wr_u32(&s, uint32_t(kBodyOff + payload));
  s.push_back(char(kVersion));
  wr_u32(&s, seq);
  s.push_back(char(RESP_BULK));
  s.push_back(char(with_rem ? kBulkFlagRemaining : 0));
  wr_u32(&s, n);
  for (uint32_t base = 0; base < n; base += 8) {
    uint8_t byte = 0;
    for (uint32_t j = 0; j < 8 && base + j < n; j++) {
      byte |= uint8_t((verdict[base + j] == 1 ? 1u : 0u) << j);
    }
    s.push_back(char(byte));
  }
  if (with_rem) {
    s.append(reinterpret_cast<const char*>(remaining), 4ull * n);
  }
  return s;
}

// Per-frame hot-key aggregation for the heavy-hitter sketch: the bulk
// lane's keys never materialize in Python (KeyBlob end to end — the
// PR-2 exemption), so the C side mirrors the scalar batch lane's
// "top-K per batch" feed: one bounded open-addressed pass over the
// frame, then the frame's heaviest rows land in a ring the pump drains
// into the sketch. Telemetry-grade: scratch overflow and hash-identity
// merging cost tail fidelity, never head weight.
constexpr size_t kHotScratchSlots = 512;  // power of two
constexpr size_t kHotScratchProbe = 4;
constexpr size_t kHotTopPerFrame = 32;
constexpr size_t kHotRingCap = 4096;

void bulk_hot_feed(Shard* sh, const uint8_t* blob,
                   const int64_t* offs, const int64_t* counts,
                   uint64_t n) {
  // mu held.
  if (sh->hot_scratch.empty()) sh->hot_scratch.resize(kHotScratchSlots);
  sh->hot_epoch++;
  uint64_t epoch = sh->hot_epoch;
  size_t used_idx[kHotScratchSlots];
  size_t used = 0;
  for (uint64_t i = 0; i < n; i++) {
    int64_t w = counts[i];
    if (w <= 0) continue;  // probes/releases are not admission demand
    size_t klen = size_t(offs[i + 1] - offs[i]);
    if (klen == 0 || klen > kT0MaxKey) continue;
    std::string_view key(reinterpret_cast<const char*>(blob) + offs[i],
                         klen);
    uint64_t hsh = t0_hash(key);
    size_t idx = size_t(hsh) & (kHotScratchSlots - 1);
    for (size_t pr = 0; pr < kHotScratchProbe; pr++) {
      size_t at = (idx + pr) & (kHotScratchSlots - 1);
      HotSlot& s = sh->hot_scratch[at];
      if (s.epoch != epoch) {
        s.epoch = epoch;
        s.hash = hsh;
        s.row = int64_t(i);
        s.weight = double(w);
        used_idx[used++] = at;
        break;
      }
      if (s.hash == hsh) {  // hash identity suffices for telemetry
        s.weight += double(w);
        break;
      }
    }
  }
  size_t top = used < kHotTopPerFrame ? used : kHotTopPerFrame;
  if (top < used) {
    std::nth_element(used_idx, used_idx + top, used_idx + used,
                     [&](size_t x, size_t y) {
                       return sh->hot_scratch[x].weight >
                              sh->hot_scratch[y].weight;
                     });
  }
  for (size_t j = 0; j < top; j++) {
    const HotSlot& s = sh->hot_scratch[used_idx[j]];
    if (sh->hot_ring.size() >= kHotRingCap) {
      sh->hot_ring.pop_front();
      sh->hot_dropped++;
    }
    sh->hot_ring.emplace_back(
        std::string(
            reinterpret_cast<const char*>(blob) + offs[s.row],
            size_t(offs[s.row + 1] - offs[s.row])),
        s.weight);
  }
}

// Parse + decide one OP_ACQUIRE_MANY frame natively. Returns false when
// the frame does not parse as a well-formed bulk request — the caller
// routes it to the Python passthrough lane, where wire.py (the
// protocol authority) raises the exact routable error the asyncio
// server would, byte for byte. Well-formed frames never leave C unless
// rows need the store.
bool handle_bulk_frame(Shard* sh, Conn* c, const uint8_t* body,
                       size_t len) {
  // mu held (parse burst on the IO thread, or a parked-frame drain /
  // fe_set_authed replay on the loop thread).
  if (len < kBodyOff + kBulkReqHead) return false;
  const uint8_t* p = body + kBodyOff;
  uint8_t flags = p[0];
  double a = rd_f64(p + 1);
  double b = rd_f64(p + 9);
  uint64_t n = rd_u32(p + 17);
  uint8_t kind = uint8_t((flags & kBulkKindMask) >> kBulkKindShift);
  // Kinds past FWINDOW (BULK_KIND_HBUCKET's tenant extension) are
  // Python-lane shapes: wire.py either serves them (hierarchical) or
  // raises the routable error. drl-check wire-hier pins this gate.
  if (kind > BULK_KIND_FWINDOW) return false;
  if (n == 0) return false;  // degenerate frame: Python authority
  bool traced = (flags & BULK_FLAG_TRACED) != 0;
  size_t tail = traced ? kTraceTail : 0;
  if (kBodyOff + kBulkReqHead + 6 * n + tail > len) return false;
  const uint8_t* kl = p + kBulkReqHead;
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; i++) total += rd_u16(kl + 2 * i);
  if (len != kBodyOff + kBulkReqHead + 6 * n + total + tail) return false;
  const uint8_t* blob = kl + 2 * n;
  const uint8_t* cnts = blob + total;
  uint32_t seq = rd_u32(body + 1);
  uint64_t now = now_ns();

  sh->bulk_frames++;
  sh->bulk_rows += int64_t(n);
  std::vector<int64_t>& offs = sh->bulk_offsets_scratch;
  std::vector<int64_t>& cnt64 = sh->bulk_counts_scratch;
  std::vector<uint8_t>& verdict = sh->bulk_verdict_scratch;
  std::vector<float>& remaining = sh->bulk_rem_scratch;
  std::vector<int32_t>& residue = sh->bulk_residue_scratch;
  offs.resize(n + 1);
  cnt64.resize(n);
  verdict.assign(n, 2);
  remaining.assign(n, 0.0f);
  residue.clear();
  bool t0able = sh->bulk_t0 &&
                sh->owner->t0_enabled.load(std::memory_order_relaxed) &&
                kind == BULK_KIND_BUCKET;
  // Agg modes after the decide pass (see the Shard scratch block):
  // grant-all rows fill verdict/remaining lock-free afterward; per-row
  // rows were written exactly by the legacy walk; residue-all rows
  // keep verdict 2 and fall through to Python.
  constexpr uint8_t kAggGrantAll = 0;
  constexpr uint8_t kAggPerRow = 1;
  constexpr uint8_t kAggResidue = 2;
  std::vector<int32_t>& agg_of = sh->bulk_aggof_scratch;
  size_t naggs = 0;
  if (t0able) {
    agg_of.assign(n, -1);
    // Epoch-stamped open table sized for the frame (2n slots, power of
    // two): no per-frame clear, collisions resolved by key bytes — a
    // hash-identity merge would fuse two tenants' budgets.
    size_t want = 2;
    while (want < 2 * n) want <<= 1;
    if (sh->bulk_aggtab_epoch.size() < want) {
      sh->bulk_aggtab_epoch.assign(want, 0);
      sh->bulk_aggtab_idx.assign(want, -1);
    }
    sh->bulk_agg_epoch++;
    sh->agg_hash.clear();
    sh->agg_first.clear();
    sh->agg_nrows.clear();
    sh->agg_total.clear();
    sh->agg_mode.clear();
  }
  size_t aggmask = t0able ? sh->bulk_aggtab_epoch.size() - 1 : 0;
  uint64_t aggepoch = sh->bulk_agg_epoch;
  int64_t off = 0;
  double permits_local = 0.0;
  offs[0] = 0;
  // Pass 1 — parse + aggregate. Tier-0-eligible rows group by key (one
  // agg per distinct key); nothing is decided and no lock is touched
  // while the row loop runs.
  for (uint64_t i = 0; i < n; i++) {
    size_t klen = rd_u16(kl + 2 * i);
    std::string_view key(
        reinterpret_cast<const char*>(blob) + off, klen);
    off += int64_t(klen);
    offs[i + 1] = off;
    int64_t count = int64_t(rd_u32(cnts + 4 * i));
    cnt64[i] = count;
    if (t0able && count > 0 && klen <= kT0MaxKey) {
      uint64_t hsh = t0_hash(key);
      size_t slot = size_t(hsh) & aggmask;
      int32_t agg = -1;
      for (;;) {
        if (sh->bulk_aggtab_epoch[slot] != aggepoch) {
          agg = int32_t(naggs++);
          sh->bulk_aggtab_epoch[slot] = aggepoch;
          sh->bulk_aggtab_idx[slot] = agg;
          sh->agg_hash.push_back(hsh);
          sh->agg_first.push_back(int32_t(i));
          sh->agg_nrows.push_back(1);
          sh->agg_total.push_back(count);
          sh->agg_mode.push_back(kAggResidue);
          break;
        }
        int32_t cand = sh->bulk_aggtab_idx[slot];
        if (sh->agg_hash[size_t(cand)] == hsh) {
          int32_t fr = sh->agg_first[size_t(cand)];
          std::string_view fkey(
              reinterpret_cast<const char*>(blob) + offs[fr],
              size_t(offs[fr + 1] - offs[fr]));
          if (fkey == key) {
            agg = cand;
            sh->agg_nrows[size_t(cand)]++;
            sh->agg_total[size_t(cand)] += count;
            break;
          }
        }
        slot = (slot + 1) & aggmask;
      }
      agg_of[i] = agg;
    }
  }
  // Pass 2 — decide, ONE lock acquisition on the shard's own tier-0
  // slice and one envelope decision per KEY. The grant-all fast path
  // (the hot steady state: the key's summed ask fits this shard's
  // budget share) draws the aggregate down in O(1) under the lock; a
  // key near its envelope edge falls back to the exact per-row legacy
  // walk under the same lock, so boundary semantics — progressive
  // remaining, confident denies, fall-through — stay bit-identical to
  // the scalar lane's. Same replica slice, budgets, and counters as
  // the scalar ACQUIRE lane: a bulk row's local grant draws down the
  // exact envelope a scalar grant would (one epsilon budget, not two).
  if (t0able && naggs > 0) {
    sh->agg_before.assign(naggs, 0.0);
    sh->agg_lastrem.assign(naggs, 0.0);
    T0Part* part = t0_slice(sh);
    if (part != nullptr) {
      bool any_per_row = false;
      std::lock_guard<T0SpinMutex> lk(part->mu);
      for (size_t g = 0; g < naggs; g++) {
        int32_t fr = sh->agg_first[g];
        std::string_view key(
            reinterpret_cast<const char*>(blob) + offs[fr],
            size_t(offs[fr + 1] - offs[fr]));
        T0Entry* e = t0_find(part, key, sh->agg_hash[g], a, b);
        if (e == nullptr ||
            now - e->last_ack_ns > part->cfg.stale_ns) {
          part->misses += sh->agg_nrows[g];
          continue;  // kAggResidue: every row falls through identically
        }
        e->last_touch_ns = now;
        double total = double(sh->agg_total[g]);
        if (e->admitted + total <= e->budget) {
          sh->agg_mode[g] = kAggGrantAll;
          sh->agg_before[g] = e->admitted;
          sh->agg_lastrem[g] = e->last_remaining;
          e->admitted += total;
          e->pending += total;
          part->hits += sh->agg_nrows[g];
          part->grant_tokens += total;
          permits_local += total;
          continue;
        }
        // Envelope edge: mark for the exact legacy walk below. The
        // walk runs as ONE row pass over the frame for ALL boundary
        // keys together — a per-key rescan would be
        // O(boundary keys × rows) under this lock, and the boundary
        // regime (budget shares drawn down between sync rounds) is
        // exactly where frames get slow, not where they may.
        sh->agg_mode[g] = kAggPerRow;
        any_per_row = true;
      }
      if (any_per_row) {
        for (uint64_t i = 0; i < n; i++) {
          int32_t g = agg_of[i];
          if (g < 0 || sh->agg_mode[size_t(g)] != kAggPerRow) continue;
          std::string_view rkey(
              reinterpret_cast<const char*>(blob) + offs[i],
              size_t(offs[i + 1] - offs[i]));
          double rem = 0.0;
          int v = t0_decide_locked(part, rkey, sh->agg_hash[size_t(g)],
                                   cnt64[i], a, b, &rem, now);
          if (v >= 0) {
            verdict[i] = uint8_t(v);
            remaining[i] = float(rem);
            if (v == 1) permits_local += double(cnt64[i]);
          }
        }
      }
    }
    // Lock-free fill for the grant-all keys: row j's remaining is the
    // envelope view after its own grant (last acked balance minus the
    // running admitted) — exactly the per-row walk's estimates.
    sh->agg_run.assign(naggs, 0.0);
    for (uint64_t i = 0; i < n; i++) {
      int32_t g = agg_of[i];
      if (g < 0 || sh->agg_mode[size_t(g)] != kAggGrantAll) continue;
      sh->agg_run[size_t(g)] += double(cnt64[i]);
      verdict[i] = 1;
      remaining[i] = float(std::max(
          sh->agg_lastrem[size_t(g)] -
              (sh->agg_before[size_t(g)] + sh->agg_run[size_t(g)]),
          0.0));
    }
  }
  for (uint64_t i = 0; i < n; i++) {
    if (verdict[i] == 2) residue.push_back(int32_t(i));
  }
  if (sh->bulk_hot) {
    if (t0able && naggs > 0) {
      // The decide pass already aggregated this frame by key — feed
      // the sketch from the aggs instead of re-hashing every row
      // (bulk_hot_feed's own pass exists for frames the tier-0 lane
      // never grouped: windows, disabled tier-0). Same top-K bound.
      size_t top = naggs < kHotTopPerFrame ? naggs : kHotTopPerFrame;
      static_assert(kHotTopPerFrame > 0, "top-K feed");
      std::vector<size_t> order(naggs);
      for (size_t g = 0; g < naggs; g++) order[g] = g;
      if (top < naggs) {
        std::nth_element(order.begin(), order.begin() + top, order.end(),
                         [&](size_t x, size_t y) {
                           return sh->agg_total[x] > sh->agg_total[y];
                         });
      }
      for (size_t j = 0; j < top; j++) {
        size_t g = order[j];
        int32_t fr0 = sh->agg_first[g];
        if (sh->agg_total[g] <= 0 ||
            offs[fr0 + 1] - offs[fr0] == 0) {
          continue;  // empty keys stay out of the sketch, matching
                     // bulk_hot_feed's klen==0 filter
        }
        if (sh->hot_ring.size() >= kHotRingCap) {
          sh->hot_ring.pop_front();
          sh->hot_dropped++;
        }
        int32_t fr = sh->agg_first[g];
        sh->hot_ring.emplace_back(
            std::string(
                reinterpret_cast<const char*>(blob) + offs[fr],
                size_t(offs[fr + 1] - offs[fr])),
            double(sh->agg_total[g]));
      }
    } else {
      bulk_hot_feed(sh, blob, offs.data(), cnt64.data(), n);
    }
  }
  sh->bulk_rows_local += int64_t(n) - int64_t(residue.size());
  sh->bulk_permits_local += permits_local;
  if (residue.empty()) {
    // Whole frame decided locally: encode + queue RESP_BULK without
    // ever leaving this thread — the all-hot fast path.
    std::string resp = encode_bulk_reply(
        seq, (flags & kBulkFlagRemaining) != 0, uint32_t(n),
        verdict.data(), remaining.data());
    queue_to_conn(c, resp.data(), resp.size());
    uint64_t t_end = now_ns();
    if (traced) {
      const uint8_t* tp = body + len - kTraceTail;
      uint64_t hi, lo, parent;
      std::memcpy(&hi, tp, 8);
      std::memcpy(&lo, tp + 8, 8);
      std::memcpy(&parent, tp + 16, 8);
      bool all = true;
      for (uint64_t i = 0; i < n; i++) all = all && verdict[i] == 1;
      trace_ring_push_raw(sh, hi, lo, parent,
                          uint8_t(1 | (tp[24] & 1) << 1),
                          OP_ACQUIRE_MANY, all, now, t_end);
    }
    hist_record(sh, double(t_end - now) * 1e-9);
    sh->requests_served++;
    sh->bulk_frames_local++;
    c->cur_bulk = 0;  // nothing inflight: chained successors may run
    return true;
  }
  BulkJob job;
  job.id = sh->next_bulk_id++;
  job.conn_id = c->id;
  job.seq = seq;
  job.flags = flags;
  job.kind = kind;
  job.with_remaining = (flags & kBulkFlagRemaining) != 0;
  job.a = a;
  job.b = b;
  job.n = uint32_t(n);
  job.blob.assign(reinterpret_cast<const char*>(blob), size_t(total));
  job.offsets = offs;
  job.counts = cnt64;
  job.verdict = verdict;
  job.remaining = remaining;
  job.residue = residue;
  job.t_ns = now;
  if (traced) {
    const uint8_t* tp = body + len - kTraceTail;
    std::memcpy(&job.tr_hi, tp, 8);
    std::memcpy(&job.tr_lo, tp + 8, 8);
    std::memcpy(&job.tr_parent, tp + 16, 8);
    job.tr_flags = uint8_t(1 | (tp[24] & 1) << 1);
  }
  sh->bulk_rows_residue += int64_t(job.residue.size());
  c->cur_bulk = job.id;
  sh->bulk_ready.push_back(job.id);
  sh->bulk_inflight.emplace(job.id, std::move(job));
  sh->cv.notify_one();
  return true;
}

// Decide one un-parked bulk frame: native when well-formed, else the
// Python lane — and once a frame of a chain lands on the Python lane,
// its chained successors follow it there (the server's _bulk_tails
// keeps their order; deciding them natively would race the
// predecessor's reply).
void process_bulk_frame(Shard* sh, Conn* c, const uint8_t* body,
                        size_t len) {
  // mu held.
  bool chained =
      len > kBodyOff && (body[kBodyOff] & kBulkFlagChained) != 0;
  if (chained && c->bulk_pt_tail) {
    to_passthrough(sh, c, body, len);
    return;  // bulk_pt_tail stays set for the rest of the chain
  }
  if (!handle_bulk_frame(sh, c, body, len)) {
    to_passthrough(sh, c, body, len);  // malformed: Python errors
    c->bulk_pt_tail = true;
    return;
  }
  c->bulk_pt_tail = false;
}

void drain_parked(Shard* sh, Conn* c) {
  // mu held. Un-park chained successors once the connection has no
  // inflight bulk job; stops when a drained frame starts a new one (its
  // completion resumes the drain) or the connection goes bad.
  while (!c->parked_bulk.empty() && c->cur_bulk == 0 && !c->closing) {
    std::string f = std::move(c->parked_bulk.front());
    c->parked_bulk.pop_front();
    c->parked_bytes -= f.size();
    process_bulk_frame(sh, c,
                       reinterpret_cast<const uint8_t*>(f.data()),
                       f.size());
  }
  flush_queued(sh, c);
}

void finish_bulk_job(Shard* sh, int64_t job_id) {
  // mu held. Erase a completed/abandoned job and un-park the
  // connection's chained successors (the asyncio server's per-
  // connection bulk_tail contract, kept here by parking raw frames
  // until the predecessor's reply is encoded).
  auto it = sh->bulk_inflight.find(job_id);
  if (it == sh->bulk_inflight.end()) return;
  uint64_t conn_id = it->second.conn_id;
  sh->bulk_inflight.erase(it);
  auto itc = sh->conns.find(conn_id);
  if (itc != sh->conns.end()) {
    Conn* c = itc->second;
    if (c->cur_bulk == job_id) c->cur_bulk = 0;
    drain_parked(sh, c);
  }
  maybe_flush_after_complete(sh);
}

// Handle one complete frame body. Returns false if the connection must
// close (protocol breakage — an error reply is already queued). Called
// from parse_frames (IO thread) and from fe_set_authed's held-frame
// replay (loop thread); mu held either way.
bool handle_frame(Shard* sh, Conn* c, const uint8_t* body, size_t len) {
  if (c->closing) return true;  // replies would be dropped: stop mutating
                                // store state for a dying connection
  uint8_t ver = body[0];
  uint32_t seq = rd_u32(body + 1);
  uint8_t rawop = body[5];
  // The trace flag gates a 25-byte tail after the payload; the base op
  // routes. Non-hot flagged ops fall to the passthrough default with
  // the ORIGINAL body — Python's wire module strips the tail there.
  bool traced = (rawop & TRACE_FLAG) != 0;
  uint8_t op = rawop & uint8_t(~TRACE_FLAG);
  if (ver != kVersion) {
    std::string err = encode_error(seq, "protocol version mismatch");
    send_to_conn(sh, c, err.data(), err.size());
    return false;
  }
  if (!c->authed) {
    if (op == OP_HELLO) {
      c->auth_pending = true;  // Python resolves via fe_set_authed
    } else if (c->auth_pending) {
      // Pipelined behind an unresolved HELLO (legal — the asyncio path
      // reads frames sequentially so ordering makes this work there;
      // here auth resolves asynchronously, so park the frame until it
      // does). Bounded: a flood before auth is protocol abuse.
      if (c->held_bytes + len > kMaxHeld) {
        std::string err = encode_error(seq, "auth pending: too much data");
        send_to_conn(sh, c, err.data(), err.size());
        return false;
      }
      c->held.emplace_back(reinterpret_cast<const char*>(body), len);
      c->held_bytes += len;
      return true;
    } else {
      std::string err =
          encode_error(seq, "authentication required: send HELLO first");
      send_to_conn(sh, c, err.data(), err.size());
      return false;
    }
  }
  switch (op) {
      case OP_ACQUIRE:
      case OP_WINDOW:
      case OP_FWINDOW:
      case OP_SEMA: {
        // [u16 klen][key utf-8][i32 count][f64 a][f64 b] (+ trace tail)
        size_t tail = traced ? kTraceTail : 0;
        if (len < kBodyOff + 2 + 20 + tail) {
          std::string err = encode_error(seq, "truncated request");
          send_to_conn(sh, c, err.data(), err.size());
          return false;
        }
        uint16_t klen = rd_u16(body + kBodyOff);
        if (len != kBodyOff + 2 + size_t(klen) + 20 + tail) {
          std::string err = encode_error(seq, "malformed request");
          send_to_conn(sh, c, err.data(), err.size());
          return false;
        }
        const uint8_t* kp = body + kBodyOff + 2;
        Item it;
        it.conn_id = c->id;
        it.seq = seq;
        it.op = op;
        it.key.assign(reinterpret_cast<const char*>(kp), klen);
        it.count = rd_i32(kp + klen);
        it.a = rd_f64(kp + klen + 4);
        it.b = rd_f64(kp + klen + 12);
        it.t_ns = now_ns();
        if (traced) {
          const uint8_t* tp = body + len - kTraceTail;
          std::memcpy(&it.tr_hi, tp, 8);
          std::memcpy(&it.tr_lo, tp + 8, 8);
          std::memcpy(&it.tr_parent, tp + 16, 8);
          it.tr_flags = uint8_t(1 | (tp[24] & 1) << 1);
        }
        if (op == OP_ACQUIRE && it.count > 0 &&
            sh->owner->t0_enabled.load(std::memory_order_relaxed)) {
          // Tier-0: answer from the local replica when it is confident
          // either way; zero-permit probes and every other op keep the
          // exact device path. A traced local decision leaves a span
          // record for the Python harvest — locally-granted requests
          // still trace.
          double rem = 0.0;
          int verdict = t0_decide(t0_slice(sh), it.key, it.count, it.a,
                                  it.b, &rem, it.t_ns);
          if (verdict >= 0) {
            std::string resp = encode_decision(seq, verdict == 1, rem);
            queue_to_conn(c, resp.data(), resp.size());
            uint64_t t_end = now_ns();
            if (traced) trace_ring_push(sh, it, verdict == 1, t_end);
            hist_record(sh, double(t_end - it.t_ns) * 1e-9);
            sh->requests_served++;
            break;
          }
        }
        if (sh->pending.empty()) sh->pending_oldest_ns = it.t_ns;
        sh->pending.push_back(std::move(it));
        break;
      }
      case OP_PING: {
        std::string resp = encode_empty(seq);
        queue_to_conn(c, resp.data(), resp.size());
        sh->requests_served++;  // the asyncio server counts pings too
        break;
      }
      case OP_ACQUIRE_MANY: {
        if (!sh->bulk_native) {
          // The pump never armed the lane (older Python half, or the
          // operator disabled it): round-7 passthrough behavior.
          to_passthrough(sh, c, body, len);
          break;
        }
        bool chained =
            len > kBodyOff && (body[kBodyOff] & kBulkFlagChained) != 0;
        bool busy = c->cur_bulk != 0 &&
                    sh->bulk_inflight.count(c->cur_bulk) != 0;
        if (!c->parked_bulk.empty() || (chained && busy)) {
          // Chained chunk behind an in-flight predecessor (or any bulk
          // frame queued behind a parked chain — FIFO keeps relative
          // order trivially): park the raw frame; completion drains in
          // order. Bounded like the outbox: a chain backlog past the
          // budget is a dead/hostile pipeliner.
          if (c->parked_bytes + len > kMaxConnOut) {
            std::string err = encode_error(
                seq, "bulk chain backlog exceeds buffer budget");
            send_to_conn(sh, c, err.data(), err.size());
            return false;
          }
          c->parked_bulk.emplace_back(
              reinterpret_cast<const char*>(body), len);
          c->parked_bytes += len;
          break;
        }
        // Malformed / degenerate shapes go to Python inside
        // process_bulk_frame so the error reply stays byte-identical
        // to the asyncio server's — and mark the conn's bulk tail as
        // Python-side so a chained successor follows it there.
        process_bulk_frame(sh, c, body, len);
        break;
      }
      case OP_PLACEMENT:
      case OP_PLACEMENT_ANNOUNCE:
      case OP_MIGRATE_PULL:
      case OP_MIGRATE_PUSH:
      case OP_CONFIG:
      case OP_RESERVE:
      case OP_SETTLE:
      case OP_FED_LEASE:
      case OP_FED_RENEW:
      case OP_FED_RECLAIM:
      case OP_AUDIT:
      default: {
        // Placement/migration/config/reservation/federation control
        // ops, HELLO,
        // PEEK, SYNC, STATS, SAVE, unknown: Python decides (including
        // the unknown-op error) — the wire module stays the single
        // authority for every non-hot shape. ACQUIRE_MANY left this
        // list in round 8: well-formed bulk frames are native above,
        // and only malformed ones fall through so wire.py raises the
        // exact routable error.
        to_passthrough(sh, c, body, len);
        break;
      }
  }
  return true;
}

// Parse every complete frame in c->in. Returns false if the connection
// must close (an error reply is already queued).
bool parse_frames(Shard* sh, Conn* c) {
  // mu held.
  for (;;) {
    if (c->closing) {  // drop pipelined input behind a fatal reply — the
      c->in_off = c->in.size();  // store must not mutate for dead replies
      break;
    }
    size_t avail = c->in.size() - c->in_off;
    if (avail < 4) break;
    const uint8_t* p = c->in.data() + c->in_off;
    uint32_t len = rd_u32(p);
    if (len < kBodyOff || len > kMaxFrame) {
      std::string err = encode_error(0, "bad frame length");
      send_to_conn(sh, c, err.data(), err.size());
      return false;
    }
    if (avail < 4 + size_t(len)) break;
    const uint8_t* body = p + 4;
    c->in_off += 4 + len;
    if (!handle_frame(sh, c, body, len)) return false;
  }
  // Compact the read buffer once the parsed prefix dominates.
  if (c->in_off > 0 && (c->in_off == c->in.size() || c->in_off > 65536)) {
    c->in.erase(c->in.begin(), c->in.begin() + ptrdiff_t(c->in_off));
    c->in_off = 0;
  }
  // One send() for the whole burst's queued replies (tier-0/PING).
  flush_queued(sh, c);
  return true;
}

void arm_deadline(Shard* sh) {
  // mu held. Arm the timerfd for the oldest pending request's flush
  // deadline (ns precision — this is why not epoll_wait's ms timeout).
  bool want = !sh->pending.empty();
  if (!want && !sh->tfd_armed) return;  // already disarmed: skip syscall
  sh->tfd_armed = want;
  itimerspec its{};
  if (!sh->pending.empty()) {
    uint64_t due = sh->pending_oldest_ns + sh->deadline_ns;
    uint64_t now = now_ns();
    uint64_t delta = due > now ? due - now : 1;
    its.it_value.tv_sec = time_t(delta / 1000000000ull);
    its.it_value.tv_nsec = long(delta % 1000000000ull);
  }  // pending empty => zero itimerspec disarms
  count_sys(sh);
  timerfd_settime(sh->tfd, 0, &its, nullptr);
}

void io_loop(Shard* sh) {
  epoll_event events[128];
  for (;;) {
    count_sys(sh);
    int n = epoll_wait(sh->epfd, events, 128, -1);
    if (sh->owner->stopping.load()) break;
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    std::unique_lock<FeMutex> lk(sh->mu);
    for (int i = 0; i < n; i++) {
      uint64_t tag = events[i].data.u64;
      if (tag == 0) {  // listen socket
        for (;;) {
          count_sys(sh);
          int cfd = accept4(sh->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          int one = 1;
          count_sys(sh, 2);  // setsockopt + epoll_ctl below
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          Conn* c = new Conn();
          c->fd = cfd;
          c->id = sh->next_conn_id++;
          c->authed = !sh->require_auth;
          sh->conns[c->id] = c;
          sh->connections_served++;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.u64 = c->id;
          epoll_ctl(sh->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      if (tag == 1) {  // eventfd: stop/wake
        uint64_t junk;
        count_sys(sh);
        while (read(sh->evfd, &junk, 8) == 8) {
          count_sys(sh);
        }
        continue;
      }
      if (tag == 2) {  // timerfd: flush deadline
        uint64_t junk;
        count_sys(sh);
        while (read(sh->tfd, &junk, 8) == 8) {
          count_sys(sh);
        }
        sh->tfd_armed = false;  // one-shot timer disarmed itself
        flush_pending(sh, /*include_tail=*/true);  // deadline: all due
        continue;
      }
      auto itc = sh->conns.find(tag);
      if (itc == sh->conns.end()) continue;  // closed earlier this burst
      Conn* c = itc->second;
      uint32_t evs = events[i].events;
      if (evs & (EPOLLHUP | EPOLLERR)) {
        close_conn(sh, c);
        continue;
      }
      if (evs & EPOLLOUT) {
        flush_out(sh, c);
        itc = sh->conns.find(tag);
        if (itc == sh->conns.end()) continue;  // flush closed it
      }
      if (evs & EPOLLIN) {
        bool eof = false, ok = true;
        for (;;) {
          uint8_t buf[65536];
          count_sys(sh);
          ssize_t r = ::recv(c->fd, buf, sizeof buf, 0);
          if (r > 0) {
            c->in.insert(c->in.end(), buf, buf + r);
            if (c->in.size() - c->in_off > 2 * size_t(kMaxFrame) + 4) {
              // Parse eagerly so a pipelining client can't balloon RAM.
              ok = parse_frames(sh, c);
              if (!ok) break;
            }
            continue;
          }
          if (r == 0) {
            eof = true;
            break;
          }
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          eof = true;  // ECONNRESET et al.
          break;
        }
        if (ok) ok = parse_frames(sh, c);
        if (!ok || eof) {
          if (!ok && !c->out.empty()) {
            c->closing = true;  // let the error reply drain first
            flush_out(sh, c);
          } else {
            close_conn(sh, c);
          }
          continue;
        }
      }
    }
    // Flush decision once per event burst (so one TCP segment's worth of
    // pipelined frames coalesces into one batch, not N):
    if (!sh->pending.empty()) {
      // "Idle" means nothing is queued for OR being served by Python
      // (ready empty AND inflight empty): batching only pays when a
      // flush is already running — while one is, arrivals accumulate so
      // the batch size adapts to load (same reasoning as MicroBatcher's
      // flush-on-idle, benchmarks/RESULTS.md).
      bool idle_pump = sh->pump_waiting && sh->ready.empty() &&
                       sh->pt.empty() && sh->inflight.empty() &&
                       sh->bulk_ready.empty() && sh->bulk_inflight.empty();
      bool due = now_ns() >= sh->pending_oldest_ns + sh->deadline_ns;
      if (sh->pending.size() >= sh->max_batch || idle_pump || due) {
        // Size-only trigger holds the sub-max_batch tail to coalesce;
        // idle/deadline triggers drain it (see flush_pending).
        flush_pending(sh, /*include_tail=*/idle_pump || due);
      }
    }
    arm_deadline(sh);
  }
  // Shutdown: fail the pump out of its wait and close every socket.
  std::lock_guard<FeMutex> lk(sh->mu);
  for (auto& [id, c] : sh->conns) {
    ::close(c->fd);
    delete c;
  }
  sh->conns.clear();
  sh->cv.notify_all();
}

void wake_io(Shard* sh) {
  uint64_t one = 1;
  count_sys(sh);
  ssize_t r = write(sh->evfd, &one, 8);
  (void)r;
}

// ---------------------------------------------------------------------
// io_uring transport (round 16). The reply bytes are the spec — every
// frame still flows through the SAME parse_frames / handle_frame /
// handle_bulk_frame / flush_pending machinery as the epoll loop; only
// how bytes cross the kernel boundary changes. Contract notes:
//   * order: at most ONE SEND op in flight per connection, staged from
//     wbuf (the bytes an in-flight op points at) with out as the
//     overflow queue — submission order IS reply order.
//   * teardown: a Conn with owed CQEs parks in Shard::dying until its
//     uring_ops drains to zero (a kernel op holds pointers into the
//     Conn), then frees. close_conn and every helper branch here when
//     sh->uring is set, so callers never see transport-specific state.
//   * graceful close: when the farewell bytes are fully staged, a CLOSE
//     is linked behind the SEND (IOSQE_IO_LINK). The kernel breaks a
//     link on error OR short transfer, so the close runs only when the
//     goodbye actually drained — otherwise the send CQE re-arms.
// ---------------------------------------------------------------------

// Runtime feature probe. Returns 1 when the 5.19+ feature level this
// transport needs is present; 0 with a human-readable reason otherwise.
// Sanitizer builds gate the transport off: the ring's kernel-side
// writes into shared memory are invisible to ASan/TSan instrumentation.
int uring_probe(std::string* reason) {
#if defined(DRL_TSAN) || defined(DRL_ASAN)
  if (reason) *reason = "sanitizer build: uring transport feature-gated off";
  return 0;
#else
  const char* no = std::getenv("DRL_TPU_NO_URING");
  if (no != nullptr && *no != '\0' && std::string(no) != "0") {
    if (reason) *reason = "disabled by DRL_TPU_NO_URING";
    return 0;
  }
  const char* deny = std::getenv("DRL_TPU_URING_FAKE_DENY");
  if (deny != nullptr && *deny != '\0' && std::string(deny) != "0") {
    // Test hook: behave exactly as a seccomp filter returning EPERM.
    if (reason) *reason = "io_uring_setup denied (EPERM, simulated seccomp)";
    return 0;
  }
  DrlUringParams p{};
  int fd = sys_uring_setup(4, &p);
  if (fd < 0) {
    if (reason) {
      if (errno == ENOSYS) {
        *reason = "kernel lacks io_uring (ENOSYS)";
      } else if (errno == EPERM) {
        *reason = "io_uring_setup denied (EPERM — seccomp or "
                  "kernel.io_uring_disabled)";
      } else {
        *reason = std::string("io_uring_setup failed: ") + strerror(errno);
      }
    }
    return 0;
  }
  DrlProbe probe{};
  int rc = sys_uring_register(fd, kRegRegisterProbe, &probe, 48);
  ::close(fd);
  if (rc < 0) {
    if (reason) *reason = "io_uring opcode probe unsupported (pre-5.6)";
    return 0;
  }
  if (probe.last_op < kOpSocketProxy) {
    // Multishot recv and PBUF_RING have no probe bit; IORING_OP_SOCKET
    // shipped in the same release (5.19) and is the documented proxy.
    if (reason) {
      *reason = "kernel predates the 5.19 feature level "
                "(multishot recv + provided-buffer rings)";
    }
    return 0;
  }
  const uint8_t need[] = {kOpAccept, kOpAsyncCancel, kOpClose,
                          kOpRead,   kOpSend,        kOpRecv};
  for (uint8_t op : need) {
    if (op >= probe.ops_len || (probe.ops[op].flags & 1) == 0) {
      if (reason) {
        *reason = "required io_uring opcode " + std::to_string(int(op)) +
                  " not supported";
      }
      return 0;
    }
  }
  if (reason) reason->clear();
  return 1;
#endif
}

void uring_free_ring(UringRing* r) {
  if (r == nullptr) return;
  if (r->buf_ring != nullptr) munmap(r->buf_ring, r->buf_ring_len);
  if (r->buf_pool != nullptr) munmap(r->buf_pool, r->buf_pool_len);
  if (r->sqes != nullptr) munmap(r->sqes, r->sqes_len);
  if (r->cq_map != nullptr && r->cq_map != r->sq_map) {
    munmap(r->cq_map, r->cq_map_len);
  }
  if (r->sq_map != nullptr) munmap(r->sq_map, r->sq_map_len);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

// Return one provided buffer to the recv pool. Entry 0's resv overlays
// the ring tail; the release store publishes the refilled slot to the
// kernel (mirrors liburing's io_uring_buf_ring_advance).
void uring_recycle_buf(UringRing* r, uint16_t bid) {
  DrlBuf* e = &r->buf_ring[r->buf_tail & (kUringBufCount - 1)];
  e->addr = uint64_t(reinterpret_cast<uintptr_t>(r->buf_pool)) +
            uint64_t(bid) * kUringBufSize;
  e->len = uint32_t(kUringBufSize);
  e->bid = bid;
  r->buf_tail++;
  reinterpret_cast<std::atomic<uint16_t>*>(&r->buf_ring[0].resv)
      ->store(r->buf_tail, std::memory_order_release);
}

bool uring_setup_shard(Shard* sh, bool sqpoll) {
  std::string reason;
  if (uring_probe(&reason) == 0) {
    sh->uring_reason = reason;
    return false;
  }
  DrlUringParams p{};
  p.flags = kUringSetupCqsize | kUringSetupClamp;
  p.cq_entries = kUringCqEntries;
  if (sqpoll) {
    p.flags |= kUringSetupSqpoll;
    p.sq_thread_idle = 50;  // ms the kernel SQ thread spins before napping
  }
  int fd = sys_uring_setup(kUringSqEntries, &p);
  if (fd < 0 && sqpoll) {
    // SQPOLL needs CAP_SYS_NICE pre-5.11 and can be policy-refused;
    // fall one notch to plain uring rather than all the way to epoll.
    sqpoll = false;
    p = DrlUringParams{};
    p.flags = kUringSetupCqsize | kUringSetupClamp;
    p.cq_entries = kUringCqEntries;
    fd = sys_uring_setup(kUringSqEntries, &p);
    sh->uring_reason = "sqpoll refused by kernel; running uring without it";
  }
  if (fd < 0) {
    sh->uring_reason = std::string("io_uring_setup failed: ") +
                       strerror(errno);
    return false;
  }
  UringRing* r = new UringRing();
  r->fd = fd;
  r->sqpoll = sqpoll;
  size_t sq_len = size_t(p.sq_off.array) + p.sq_entries * sizeof(uint32_t);
  size_t cq_len = size_t(p.cq_off.cqes) + p.cq_entries * sizeof(DrlCqe);
  bool single = (p.features & kUringFeatSingleMmap) != 0;
  if (single) sq_len = cq_len = std::max(sq_len, cq_len);
  void* sq = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, fd, long(kUringOffSqRing));
  if (sq == MAP_FAILED) {
    sh->uring_reason = "sq ring mmap failed";
    uring_free_ring(r);
    return false;
  }
  r->sq_map = sq;
  r->sq_map_len = sq_len;
  if (single) {
    r->cq_map = sq;
    r->cq_map_len = sq_len;
  } else {
    void* cq = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, long(kUringOffCqRing));
    if (cq == MAP_FAILED) {
      sh->uring_reason = "cq ring mmap failed";
      uring_free_ring(r);
      return false;
    }
    r->cq_map = cq;
    r->cq_map_len = cq_len;
  }
  r->sqes_len = p.sq_entries * sizeof(DrlSqe);
  void* sqes = mmap(nullptr, r->sqes_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, long(kUringOffSqes));
  if (sqes == MAP_FAILED) {
    sh->uring_reason = "sqe array mmap failed";
    uring_free_ring(r);
    return false;
  }
  r->sqes = static_cast<DrlSqe*>(sqes);
  auto* sqb = static_cast<uint8_t*>(r->sq_map);
  r->sq_head =
      reinterpret_cast<std::atomic<uint32_t>*>(sqb + p.sq_off.head);
  r->sq_tail =
      reinterpret_cast<std::atomic<uint32_t>*>(sqb + p.sq_off.tail);
  r->sq_mask = *reinterpret_cast<uint32_t*>(sqb + p.sq_off.ring_mask);
  r->sq_array = reinterpret_cast<uint32_t*>(sqb + p.sq_off.array);
  r->sq_flags =
      reinterpret_cast<std::atomic<uint32_t>*>(sqb + p.sq_off.flags);
  auto* cqb = static_cast<uint8_t*>(r->cq_map);
  r->cq_head =
      reinterpret_cast<std::atomic<uint32_t>*>(cqb + p.cq_off.head);
  r->cq_tail =
      reinterpret_cast<std::atomic<uint32_t>*>(cqb + p.cq_off.tail);
  r->cq_mask = *reinterpret_cast<uint32_t*>(cqb + p.cq_off.ring_mask);
  r->cqes = reinterpret_cast<DrlCqe*>(cqb + p.cq_off.cqes);
  // Registered files: the three immortal control fds only (see the
  // UringRing comment for why conn sockets stay out of the table).
  int files[3] = {sh->listen_fd, sh->evfd, sh->tfd};
  if (sys_uring_register(fd, kRegRegisterFiles, files, 3) < 0) {
    sh->uring_reason = "IORING_REGISTER_FILES refused";
    uring_free_ring(r);
    return false;
  }
  // Provided-buffer ring + the registered buffer pool it points into.
  r->buf_ring_len = kUringBufCount * sizeof(DrlBuf);
  if (r->buf_ring_len < 4096) r->buf_ring_len = 4096;  // page-aligned
  void* br = mmap(nullptr, r->buf_ring_len, PROT_READ | PROT_WRITE,
                  MAP_ANONYMOUS | MAP_PRIVATE, -1, 0);
  if (br == MAP_FAILED) {
    r->buf_ring_len = 0;
    sh->uring_reason = "buffer-ring mmap failed";
    uring_free_ring(r);
    return false;
  }
  r->buf_ring = static_cast<DrlBuf*>(br);
  std::memset(r->buf_ring, 0, r->buf_ring_len);
  r->buf_pool_len = size_t(kUringBufCount) * kUringBufSize;
  void* pool = mmap(nullptr, r->buf_pool_len, PROT_READ | PROT_WRITE,
                    MAP_ANONYMOUS | MAP_PRIVATE, -1, 0);
  if (pool == MAP_FAILED) {
    r->buf_pool_len = 0;
    sh->uring_reason = "buffer-pool mmap failed";
    uring_free_ring(r);
    return false;
  }
  r->buf_pool = static_cast<uint8_t*>(pool);
  DrlBufReg reg{};
  reg.ring_addr = uint64_t(reinterpret_cast<uintptr_t>(r->buf_ring));
  reg.ring_entries = kUringBufCount;
  reg.bgid = kUringBgid;
  if (sys_uring_register(fd, kRegRegisterPbufRing, &reg, 1) < 0) {
    sh->uring_reason = "IORING_REGISTER_PBUF_RING refused (pre-5.19)";
    uring_free_ring(r);
    return false;
  }
  for (uint16_t b = 0; b < kUringBufCount; b++) uring_recycle_buf(r, b);
  sh->ring = r;
  sh->uring = true;
  sh->uring_sqpoll = sqpoll;
  return true;
}

// Stage-side submit. mu held (sq_pending and the SQ tail are only ever
// touched under it; the kernel reads the published tail with its own
// acquire). SQPOLL: no enter at all unless the kernel SQ thread napped.
void uring_submit(Shard* sh) {
  UringRing* r = sh->ring;
  if (r == nullptr) return;
  if (r->sqpoll) {
    if (r->sq_pending > 0) {
      r->sqes_submitted.fetch_add(r->sq_pending, std::memory_order_relaxed);
      r->sq_pending = 0;
    }
    if (r->sq_flags->load(std::memory_order_acquire) & kUringSqNeedWakeup) {
      count_sys(sh);
      r->enters.fetch_add(1, std::memory_order_relaxed);
      sys_uring_enter(r->fd, 0, 0, kUringEnterSqWakeup);
    }
    return;
  }
  while (r->sq_pending > 0) {
    count_sys(sh);
    r->enters.fetch_add(1, std::memory_order_relaxed);
    int rc = sys_uring_enter(r->fd, r->sq_pending, 0, 0);
    if (rc > 0) {
      uint32_t done = uint32_t(rc) > r->sq_pending ? r->sq_pending
                                                   : uint32_t(rc);
      r->sqes_submitted.fetch_add(done, std::memory_order_relaxed);
      r->sq_pending -= done;
      continue;
    }
    if (rc == 0) break;
    if (errno == EINTR) continue;
    // EBUSY/EAGAIN: CQ backpressure — keep them staged, retry after
    // the loop reaps completions. Anything else: drop the stage count
    // (the SQEs are still in the ring; a later submit re-offers them).
    break;
  }
}

// Acquire one zeroed SQE slot. mu held. A full ring submits first; the
// post-submit spin matters only under SQPOLL (non-SQPOLL enter consumes
// synchronously). Returns nullptr only when the kernel cannot drain —
// callers treat that as "stage later" and set sh->uring_sweep.
DrlSqe* uring_get_sqe(Shard* sh) {
  UringRing* r = sh->ring;
  uint32_t tail = r->sq_tail->load(std::memory_order_relaxed);
  uint32_t head = r->sq_head->load(std::memory_order_acquire);
  if (tail - head >= r->sq_mask + 1) {
    uring_submit(sh);
    for (int spin = 0; spin < 65536; spin++) {
      head = r->sq_head->load(std::memory_order_acquire);
      if (tail - head < r->sq_mask + 1) break;
    }
    if (tail - head >= r->sq_mask + 1) return nullptr;
  }
  uint32_t idx = tail & r->sq_mask;
  DrlSqe* sqe = &r->sqes[idx];
  std::memset(sqe, 0, sizeof *sqe);
  r->sq_array[idx] = idx;
  r->sq_tail->store(tail + 1, std::memory_order_release);
  r->sq_pending++;
  return sqe;
}

void uring_arm_accept(Shard* sh) {
  DrlSqe* sqe = uring_get_sqe(sh);
  if (sqe == nullptr) {
    sh->uring_sweep = true;
    return;
  }
  sqe->opcode = kOpAccept;
  sqe->flags = kSqeFixedFile;
  sqe->fd = 0;  // registered slot 0: the listen socket
  sqe->ioprio = kAcceptMultishot;
  sqe->op_flags = SOCK_NONBLOCK;
  sqe->user_data = uring_ud(kUdAccept, 0);
}

void uring_arm_ctl_read(Shard* sh, int slot, uint64_t* buf, uint64_t kind) {
  DrlSqe* sqe = uring_get_sqe(sh);
  if (sqe == nullptr) {
    sh->uring_sweep = true;
    return;
  }
  sqe->opcode = kOpRead;
  sqe->flags = kSqeFixedFile;
  sqe->fd = slot;  // registered slot 1 = eventfd, 2 = timerfd
  sqe->addr = uint64_t(reinterpret_cast<uintptr_t>(buf));
  sqe->len = 8;
  sqe->user_data = uring_ud(kind, 0);
}

void uring_arm_recv(Shard* sh, Conn* c) {
  if (c->recv_armed || c->dead || c->fd < 0) return;
  DrlSqe* sqe = uring_get_sqe(sh);
  if (sqe == nullptr) {
    sh->uring_sweep = true;  // loop retries at burst end
    return;
  }
  sqe->opcode = kOpRecv;
  sqe->flags = kSqeBufferSelect;
  sqe->ioprio = kRecvMultishot;
  sqe->fd = c->fd;
  sqe->len = 0;  // the provided buffer's size caps each completion
  sqe->buf_group = kUringBgid;
  sqe->user_data = uring_ud(kUdRecv, c->id);
  c->recv_armed = true;
  c->uring_ops++;
}

// Stage (at most) one SEND for this connection. mu held. NEVER closes
// or frees the Conn (same contract as flush_queued: callers keep their
// pointer) — drained+closing teardown happens in the send CQE handler
// or the loop's sweep.
void uring_arm_send(Shard* sh, Conn* c) {
  if (sh->ring == nullptr || c->dead || c->send_inflight || c->fd < 0) {
    return;
  }
  if (c->wbuf_off >= c->wbuf.size()) {
    c->wbuf.clear();
    c->wbuf_off = 0;
    if (c->out_off >= c->out.size()) {
      c->out.clear();
      c->out_off = 0;
      if (c->closing) {
        // Nothing left to drain and no op to complete into teardown:
        // let the IO loop reap at burst end.
        sh->uring_sweep = true;
        wake_io(sh);
      }
      return;
    }
    if (c->out_off > 0) c->out.erase(0, c->out_off);
    c->out_off = 0;
    c->wbuf.swap(c->out);  // out is now empty; new replies append there
  }
  UringRing* r = sh->ring;
  // Decide the linked-CLOSE up front: acquiring the second SQE must not
  // trigger a submit between the pair (a submit would flush the SEND
  // without its link flag — the kernel only links within one batch).
  bool link_close = false;
  if (c->closing && c->out_off >= c->out.size()) {
    uint32_t tail = r->sq_tail->load(std::memory_order_relaxed);
    uint32_t head = r->sq_head->load(std::memory_order_acquire);
    link_close = (tail - head) + 2 <= r->sq_mask + 1;
  }
  DrlSqe* sqe = uring_get_sqe(sh);
  if (sqe == nullptr) {
    sh->uring_sweep = true;  // bytes stay staged in wbuf; retried later
    return;
  }
  sqe->opcode = kOpSend;
  sqe->fd = c->fd;
  sqe->addr =
      uint64_t(reinterpret_cast<uintptr_t>(c->wbuf.data() + c->wbuf_off));
  sqe->len = uint32_t(c->wbuf.size() - c->wbuf_off);
  sqe->op_flags = MSG_NOSIGNAL;
  sqe->user_data = uring_ud(kUdSend, c->id);
  if (link_close) sqe->flags |= kSqeIoLink;
  c->send_inflight = true;
  c->uring_ops++;
  if (link_close) {
    DrlSqe* cl = uring_get_sqe(sh);
    if (cl != nullptr) {
      cl->opcode = kOpClose;
      cl->fd = c->fd;
      cl->user_data = uring_ud(kUdClose, c->id);
      c->close_linked = true;
      c->uring_ops++;
    } else {
      sqe->flags = uint8_t(sqe->flags & ~kSqeIoLink);
    }
  }
}

// Free the Conn once no kernel op holds pointers into it. mu held.
void uring_reap(Shard* sh, Conn* c) {
  if (c->uring_ops != 0) return;
  if (c->fd >= 0) {
    count_sys(sh);
    ::close(c->fd);
    c->fd = -1;
  }
  sh->dying.erase(c->id);
  delete c;
}

void uring_close_conn(Shard* sh, Conn* c) {
  // mu held. Tear down now if no op is in flight; otherwise park in
  // `dying` (a multishot RECV or SEND still references this Conn) and
  // let the owed CQEs drain it.
  if (c->dead) return;
  c->dead = true;
  c->closing = true;
  sh->conns.erase(c->id);
  sh->dying[c->id] = c;
  if (c->recv_armed) {
    DrlSqe* sqe = uring_get_sqe(sh);
    if (sqe != nullptr) {
      sqe->opcode = kOpAsyncCancel;
      sqe->addr = uring_ud(kUdRecv, c->id);
      sqe->user_data = uring_ud(kUdCancel, c->id);
      c->uring_ops++;
    }
    // SQE unavailable is near-impossible (get_sqe submits+drains); the
    // armed RECV then completes on its own once the peer acts, and
    // shutdown frees `dying` unconditionally.
  }
  if (c->fd >= 0 && !c->close_linked && !c->send_inflight) {
    count_sys(sh);
    ::close(c->fd);  // recv cancel above reaps the multishot op
    c->fd = -1;
  }
  uring_reap(sh, c);
}

void uring_handle_cqe(Shard* sh, const DrlCqe& cqe) {
  // mu held, called from uring_loop only.
  UringRing* r = sh->ring;
  uint64_t kind = cqe.user_data >> 56;
  uint64_t id = cqe.user_data & ((1ull << 56) - 1);
  if (kind == kUdAccept) {
    if ((cqe.flags & kCqeFMore) == 0) uring_arm_accept(sh);
    if (cqe.res < 0) return;
    int cfd = cqe.res;
    int one = 1;
    count_sys(sh);
    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    Conn* c = new Conn();
    c->fd = cfd;
    c->id = sh->next_conn_id++;
    c->authed = !sh->require_auth;
    sh->conns[c->id] = c;
    sh->connections_served++;
    uring_arm_recv(sh, c);
    return;
  }
  if (kind == kUdEvRead) {  // eventfd: stop/wake — loop rechecks flags
    uring_arm_ctl_read(sh, 1, &r->ev_buf, kUdEvRead);
    return;
  }
  if (kind == kUdTfRead) {  // timerfd: flush deadline
    sh->tfd_armed = false;
    uring_arm_ctl_read(sh, 2, &r->tf_buf, kUdTfRead);
    flush_pending(sh, /*include_tail=*/true);
    return;
  }
  auto ita = sh->conns.find(id);
  Conn* c = ita != sh->conns.end() ? ita->second : nullptr;
  Conn* d = nullptr;
  if (c == nullptr) {
    auto itd = sh->dying.find(id);
    if (itd != sh->dying.end()) d = itd->second;
  }
  Conn* any = c != nullptr ? c : d;
  if (kind == kUdRecv) {
    if ((cqe.flags & kCqeFMore) == 0 && any != nullptr && any->recv_armed) {
      any->recv_armed = false;
      any->uring_ops--;
    }
    if (cqe.flags & kCqeFBuffer) {
      uint16_t bid = uint16_t(cqe.flags >> kCqeBufferShift);
      if (c != nullptr && !c->closing && cqe.res > 0) {
        const uint8_t* p = r->buf_pool + size_t(bid) * kUringBufSize;
        c->in.insert(c->in.end(), p, p + cqe.res);
      }
      uring_recycle_buf(r, bid);  // ALWAYS — even when the conn is gone
    }
    if (c == nullptr) {
      if (d != nullptr) uring_reap(sh, d);
      return;
    }
    if (cqe.res > 0) {
      if (!c->closing) {
        if (!parse_frames(sh, c)) {
          if (c->out_off < c->out.size() ||
              c->wbuf_off < c->wbuf.size()) {
            c->closing = true;  // drain the error reply first
            uring_arm_send(sh, c);
          } else {
            uring_close_conn(sh, c);
          }
          return;
        }
      }
      if (!c->recv_armed && !c->closing) uring_arm_recv(sh, c);
      return;
    }
    if (cqe.res == -ENOBUFS) {
      // Pool exhausted this burst; recycles above refill it — re-arm.
      uring_arm_recv(sh, c);
      return;
    }
    if (cqe.res == -ECANCELED) return;
    uring_close_conn(sh, c);  // EOF (res==0) or hard error: epoll parity
    return;
  }
  if (kind == kUdSend) {
    if (any == nullptr) return;
    any->uring_ops--;
    any->send_inflight = false;
    if (c == nullptr) {
      uring_reap(sh, d);  // teardown already ran; just drain the op
      return;
    }
    if (cqe.res < 0) {
      if (cqe.res == -ECANCELED) return;
      // Broken pipe etc. An armed linked CLOSE got -ECANCELED (its own
      // CQE decrements); the fd is still ours to close.
      c->close_linked = false;
      uring_close_conn(sh, c);
      return;
    }
    c->wbuf_off += size_t(cqe.res);
    bool drained =
        c->wbuf_off >= c->wbuf.size() && c->out_off >= c->out.size();
    if (c->close_linked) {
      if (drained) return;  // the linked CLOSE's CQE finishes teardown
      // Short send broke the link (CLOSE comes back -ECANCELED): the
      // remainder re-arms below and a fresh close links when staged.
      c->close_linked = false;
    }
    if (!drained) {
      uring_arm_send(sh, c);
      return;
    }
    c->wbuf.clear();
    c->wbuf_off = 0;
    c->out.clear();
    c->out_off = 0;
    if (c->closing) uring_close_conn(sh, c);
    return;
  }
  if (kind == kUdClose) {
    if (any == nullptr) return;
    any->uring_ops--;
    any->close_linked = false;
    if (cqe.res >= 0) any->fd = -1;  // the kernel closed it
    // res < 0 (-ECANCELED: the short-send link break): fd still open;
    // close_conn/reap below ::close it.
    if (c != nullptr) {
      uring_close_conn(sh, c);
    } else {
      uring_reap(sh, d);
    }
    return;
  }
  if (kind == kUdCancel) {
    if (any == nullptr) return;
    any->uring_ops--;
    if (any->dead) uring_reap(sh, any);
    return;
  }
}

void uring_loop(Shard* sh) {
  UringRing* r = sh->ring;
  {
    std::lock_guard<FeMutex> lk(sh->mu);
    uring_arm_accept(sh);
    uring_arm_ctl_read(sh, 1, &r->ev_buf, kUdEvRead);
    uring_arm_ctl_read(sh, 2, &r->tf_buf, kUdTfRead);
    uring_submit(sh);
  }
  std::vector<uint64_t> doomed;
  for (;;) {
    // Wait (WITHOUT mu — pump threads stage and submit under it) only
    // when the CQ is empty; completed work never blocks on the wait.
    if (r->cq_head->load(std::memory_order_relaxed) ==
        r->cq_tail->load(std::memory_order_acquire)) {
      count_sys(sh);
      r->enters.fetch_add(1, std::memory_order_relaxed);
      int rc = sys_uring_enter(r->fd, 0, 1, kUringEnterGetevents);
      if (rc < 0 && errno != EINTR && errno != EAGAIN && errno != EBUSY &&
          errno != ETIME) {
        break;  // epoll-loop parity: a hard wait error ends the shard
      }
    }
    if (sh->owner->stopping.load()) break;
    std::unique_lock<FeMutex> lk(sh->mu);
    uint32_t head = r->cq_head->load(std::memory_order_relaxed);
    uint32_t tail = r->cq_tail->load(std::memory_order_acquire);
    while (head != tail) {
      DrlCqe cqe = r->cqes[head & r->cq_mask];
      head++;
      // Publish per-entry so the kernel regains CQ space mid-burst (a
      // 4096-deep CQ can otherwise overflow under multishot recv).
      r->cq_head->store(head, std::memory_order_release);
      r->cqes_seen.fetch_add(1, std::memory_order_relaxed);
      uring_handle_cqe(sh, cqe);
      tail = r->cq_tail->load(std::memory_order_acquire);
    }
    if (sh->uring_sweep) {
      // Rare slow path: an arm hit a full SQ, or a closing conn has no
      // in-flight op to complete into teardown. Walk and repair.
      sh->uring_sweep = false;
      doomed.clear();
      for (auto& [cid, cc] : sh->conns) {
        if (cc->closing && !cc->send_inflight &&
            cc->wbuf_off >= cc->wbuf.size() &&
            cc->out_off >= cc->out.size()) {
          doomed.push_back(cid);
          continue;
        }
        if (!cc->send_inflight && (cc->wbuf_off < cc->wbuf.size() ||
                                   cc->out_off < cc->out.size())) {
          uring_arm_send(sh, cc);
        }
        if (!cc->recv_armed && !cc->closing) uring_arm_recv(sh, cc);
      }
      for (uint64_t cid : doomed) {
        auto it = sh->conns.find(cid);
        if (it != sh->conns.end()) uring_close_conn(sh, it->second);
      }
    }
    // Flush decision once per completion burst — identical policy to
    // the epoll loop (flush-on-idle + deadline + size trigger).
    if (!sh->pending.empty()) {
      bool idle_pump = sh->pump_waiting && sh->ready.empty() &&
                       sh->pt.empty() && sh->inflight.empty() &&
                       sh->bulk_ready.empty() && sh->bulk_inflight.empty();
      bool due = now_ns() >= sh->pending_oldest_ns + sh->deadline_ns;
      if (sh->pending.size() >= sh->max_batch || idle_pump || due) {
        flush_pending(sh, /*include_tail=*/idle_pump || due);
      }
    }
    arm_deadline(sh);
    uring_submit(sh);
  }
  // Shutdown: fail the pump out of its wait and free every connection,
  // parked or live — owed CQEs die with the ring (fe_stop frees it
  // after this thread joins, so no op can complete into freed memory).
  std::lock_guard<FeMutex> lk(sh->mu);
  for (auto& [id, c] : sh->conns) {
    if (c->fd >= 0) ::close(c->fd);
    delete c;
  }
  sh->conns.clear();
  for (auto& [id, c] : sh->dying) {
    if (c->fd >= 0) ::close(c->fd);
    delete c;
  }
  sh->dying.clear();
  sh->cv.notify_all();
}

// Transport-mode resolution: DRL_TPU_NO_URING trumps everything (the
// operator's kill switch), then DRL_TPU_URING ("1"/"on" → uring,
// "sqpoll"/"2" → uring+SQPOLL). Default: epoll (the portable lane).
int uring_mode_from_env(void) {
  const char* m = std::getenv("DRL_TPU_URING");
  if (m == nullptr || *m == '\0') return kUringOff;
  std::string v(m);
  if (v == "0" || v == "off") return kUringOff;
  if (v == "2" || v == "sqpoll") return kUringSqpoll;
  return kUringOn;
}

}  // namespace

extern "C" {

void* fe_start_sharded2(const char* host, int port, int max_batch,
                        int deadline_us, int require_auth, int nshards,
                        int pin_cpus, int uring_mode) {
  if (nshards < 1) nshards = 1;
  if (nshards > kMaxShards) nshards = kMaxShards;
  // The operator kill switch trumps an explicit request from Python.
  bool uring_killed = false;
  {
    const char* no = std::getenv("DRL_TPU_NO_URING");
    if (no != nullptr && *no != '\0' && std::string(no) != "0") {
      uring_killed = uring_mode != kUringOff;
      uring_mode = kUringOff;
    }
  }
  if (uring_mode != kUringOff && uring_mode != kUringOn &&
      uring_mode != kUringSqpoll) {
    uring_mode = kUringOff;
  }
  Frontend* fe = new Frontend();
  fe->uring_mode = uring_mode;
  fe->nshards = nshards;
  fe->max_batch = size_t(max_batch > 0 ? max_batch : 4096);
  fe->deadline_ns = uint64_t(deadline_us > 0 ? deadline_us : 300) * 1000ull;
  fe->require_auth = require_auth != 0;

  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  bool bad_host = inet_pton(AF_INET, host, &addr.sin_addr) != 1;
  bool failed = bad_host;
  for (int i = 0; i < nshards && !failed; i++) {
    Shard* sh = new Shard();
    sh->owner = fe;
    sh->index = i;
    sh->max_batch = fe->max_batch;
    sh->deadline_ns = fe->deadline_ns;
    sh->require_auth = fe->require_auth;
    fe->shards.push_back(sh);
    sh->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (sh->listen_fd < 0) {
      failed = true;
      break;
    }
    int one = 1;
    setsockopt(sh->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    if (nshards > 1 &&
        setsockopt(sh->listen_fd, SOL_SOCKET, SO_REUSEPORT, &one,
                   sizeof one) < 0) {
      // SO_REUSEPORT must be set on EVERY listener before bind (the
      // first included — later binds can only join a reuseport group
      // the first opted into). The kernel then hashes each incoming
      // connection's 4-tuple across the group: accept balancing with
      // no dispatch thread. Single-shard keeps the round-10 posture
      // (no REUSEPORT), so `fe_start` behavior is bit-identical.
      failed = true;
      break;
    }
    if (bind(sh->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof addr) < 0 ||
        listen(sh->listen_fd, 512) < 0) {
      failed = true;
      break;
    }
    if (i == 0) {
      // Port 0 resolves on the first bind; `addr` then carries the
      // resolved port so the sibling shards join the same group.
      socklen_t alen = sizeof addr;
      getsockname(sh->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                  &alen);
      fe->port = ntohs(addr.sin_port);
    }
    sh->epfd = epoll_create1(0);
    sh->evfd = eventfd(0, EFD_NONBLOCK);
    sh->tfd = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = 0;
    epoll_ctl(sh->epfd, EPOLL_CTL_ADD, sh->listen_fd, &ev);
    ev.data.u64 = 1;
    epoll_ctl(sh->epfd, EPOLL_CTL_ADD, sh->evfd, &ev);
    ev.data.u64 = 2;
    epoll_ctl(sh->epfd, EPOLL_CTL_ADD, sh->tfd, &ev);
  }
  if (failed) {
    for (Shard* sh : fe->shards) {
      if (sh->listen_fd >= 0) ::close(sh->listen_fd);
      if (sh->epfd >= 0) ::close(sh->epfd);
      if (sh->evfd >= 0) ::close(sh->evfd);
      if (sh->tfd >= 0) ::close(sh->tfd);
      delete sh;
    }
    delete fe;
    return nullptr;
  }
  for (int i = 0; i < nshards; i++) fe->t0parts.push_back(new T0Part());
  // Optional affinity: shard i -> the i-th CPU of the set this process
  // is ALLOWED to run on (taskset/numactl/cgroup cpusets shrink it —
  // absolute CPU ids would silently fail pthread_setaffinity_np under
  // exactly the NUMA workflow docs/OPERATIONS.md par.12 recommends).
  std::vector<int> allowed;
  if (pin_cpus != 0) {
    cpu_set_t mask;
    CPU_ZERO(&mask);
    if (sched_getaffinity(0, sizeof mask, &mask) == 0) {
      for (int c = 0; c < CPU_SETSIZE; c++) {
        if (CPU_ISSET(c, &mask)) allowed.push_back(c);
      }
    }
  }
  for (int i = 0; i < nshards; i++) {
    Shard* sh = fe->shards[size_t(i)];
    if (uring_mode != kUringOff) {
      // Per-shard graceful fallback: a shard the kernel (or seccomp)
      // refuses runs the epoll loop, records why in uring_reason, and
      // serves identically — availability over transport.
      uring_setup_shard(sh, uring_mode == kUringSqpoll);
    } else if (uring_killed) {
      sh->uring_reason = "disabled by DRL_TPU_NO_URING";
    }
    sh->io = std::thread(sh->uring ? uring_loop : io_loop, sh);
    if (!allowed.empty()) {
      cpu_set_t cpus;
      CPU_ZERO(&cpus);
      CPU_SET(allowed[size_t(i) % allowed.size()], &cpus);
      pthread_setaffinity_np(sh->io.native_handle(), sizeof cpus, &cpus);
    }
  }
  return fe;
}

void* fe_start_sharded(const char* host, int port, int max_batch,
                       int deadline_us, int require_auth, int nshards,
                       int pin_cpus) {
  // Round-11 compatibility entry: transport comes from the environment
  // (DRL_TPU_URING / DRL_TPU_NO_URING), defaulting to epoll.
  return fe_start_sharded2(host, port, max_batch, deadline_us,
                           require_auth, nshards, pin_cpus,
                           uring_mode_from_env());
}

void* fe_start(const char* host, int port, int max_batch, int deadline_us,
               int require_auth) {
  // Single-shard compatibility entry (an older Python half calls only
  // this): one listener, no SO_REUSEPORT — the round-10 behavior.
  return fe_start_sharded(host, port, max_batch, deadline_us,
                          require_auth, 1, 0);
}

int fe_shard_count(void* h) { return owner_of(h)->nshards; }

// Per-shard sub-handle, valid for every fe_* entry point: fe_wait /
// fe_batch_* / fe_bulk_* / fe_send / fe_complete address per-shard
// state (each Python pump thread drives exactly one shard), and the
// stats/harvest entries give the per-shard breakdown with it where the
// Frontend handle gives the whole-node merge.
void* fe_shard(void* h, int index) {
  Frontend* fe = owner_of(h);
  if (index < 0 || index >= fe->nshards) return nullptr;
  return fe->shards[size_t(index)];
}

int fe_port(void* h) { return owner_of(h)->port; }

// Wait for work: 1 = batch ready (use fe_batch_*), 2 = passthrough frame
// (use fe_pt_*), 3 = bulk residue job (use fe_bulk_*), 0 = timeout,
// -1 = stopping. Per-shard: each pump thread waits on its own shard.
int fe_wait(void* h, int timeout_ms) {
  Shard* sh = shard_of(h);
  std::unique_lock<FeMutex> lk(sh->mu);
  sh->pump_waiting = true;
  bool got = sh->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
    return sh->owner->stopping.load() || !sh->pt.empty() ||
           !sh->ready.empty() || !sh->bulk_ready.empty();
  });
  sh->pump_waiting = false;
  if (sh->owner->stopping.load()) return -1;
  if (!got) return 0;
  // Control ops first so STATS/HELLO can't starve behind a hot-batch
  // stream; all queues drain promptly because the pump never blocks.
  if (!sh->pt.empty()) {
    sh->cur_pt = std::move(sh->pt.front());
    sh->pt.pop_front();
    return 2;
  }
  if (!sh->ready.empty()) {
    Batch b = std::move(sh->ready.front());
    sh->ready.pop_front();
    sh->cur_batch_id = b.id;
    sh->inflight.emplace(b.id, std::move(b));
    return 1;
  }
  sh->cur_bulk_id = sh->bulk_ready.front();
  sh->bulk_ready.pop_front();
  return 3;
}

long long fe_batch_id(void* h) { return shard_of(h)->cur_batch_id; }

int fe_batch_n(void* h) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(sh->cur_batch_id);
  return it == sh->inflight.end() ? 0 : int(it->second.items.size());
}

long long fe_batch_key_bytes(void* h) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(sh->cur_batch_id);
  if (it == sh->inflight.end()) return 0;
  long long total = 0;
  for (const Item& item : it->second.items) total += (long long)item.key.size();
  return total;
}

// Copy the current batch out as parallel arrays (key blob is the
// concatenation; klens delimit it). Caller allocates via numpy.
void fe_batch_copy(void* h, char* key_blob, int32_t* klens, int32_t* counts,
                   uint8_t* ops, uint32_t* seqs, uint64_t* conn_ids,
                   double* as, double* bs) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(sh->cur_batch_id);
  if (it == sh->inflight.end()) return;
  size_t off = 0;
  size_t i = 0;
  for (const Item& item : it->second.items) {
    std::memcpy(key_blob + off, item.key.data(), item.key.size());
    off += item.key.size();
    klens[i] = int32_t(item.key.size());
    counts[i] = item.count;
    ops[i] = item.op;
    seqs[i] = item.seq;
    conn_ids[i] = item.conn_id;
    as[i] = item.a;
    bs[i] = item.b;
    i++;
  }
}

// Count the current batch's traced rows — the one-int gate the pump
// checks before paying fe_batch_traces' array allocations (at 1% head
// sampling ~99% of batches carry none).
int fe_batch_traced_n(void* h) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(sh->cur_batch_id);
  if (it == sh->inflight.end()) return 0;
  int n = 0;
  for (const Item& item : it->second.items) n += item.tr_flags & 1;
  return n;
}

// Copy the current batch's trace contexts as parallel arrays (zeros /
// flag bit 0 clear for untraced rows). Same contract as fe_batch_copy:
// call between fe_wait returning 1 and fe_complete/fe_fail.
void fe_batch_traces(void* h, uint64_t* hi, uint64_t* lo, uint64_t* parent,
                     uint8_t* flags) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(sh->cur_batch_id);
  if (it == sh->inflight.end()) return;
  size_t i = 0;
  for (const Item& item : it->second.items) {
    hi[i] = item.tr_hi;
    lo[i] = item.tr_lo;
    parent[i] = item.tr_parent;
    flags[i] = item.tr_flags;
    i++;
  }
}

// Drain up to `max` traced tier-0 local decisions (6 u64 each: hi, lo,
// parent, start_ns, dur_ns, meta). Returns the record count. A Frontend
// handle drains every shard's ring (rotating so a loud shard cannot
// starve the others); a shard handle drains just that shard.
int fe_trace_harvest(void* h, uint64_t* out, int max) {
  Frontend* fe = owner_of(h);
  std::vector<Shard*> shards = shards_of(h);
  size_t nsh = shards.size();
  size_t start = (as_frontend(h) != nullptr && nsh > 1)
                     ? fe->trace_shard % nsh
                     : 0;
  int n = 0;
  for (size_t si = 0; si < nsh && n < max; si++) {
    Shard* sh = shards[(start + si) % nsh];
    std::lock_guard<FeMutex> lk(sh->mu);
    while (n < max && !sh->trace_ring.empty()) {
      const TraceRec& r = sh->trace_ring.front();
      out[0] = r.hi;
      out[1] = r.lo;
      out[2] = r.parent;
      out[3] = r.start_ns;
      out[4] = r.dur_ns;
      out[5] = r.meta;
      out += 6;
      n++;
      sh->trace_ring.pop_front();
    }
  }
  if (as_frontend(h) != nullptr && nsh > 1) {
    fe->trace_shard = (start + 1) % nsh;
  }
  return n;
}

// Complete a batch: encode one RESP_DECISION per item, write natively,
// record serving latency (arrival -> completion, the same span the
// asyncio server's histogram covers). granted[i] == kRowSkip marks a
// row Python already answered via fe_send (per-row placement error on
// the batch lane — MOVED / handoff deferral); it gets no decision
// reply, no tier-0 install, and no second requests_served count.
constexpr uint8_t kRowSkip = 2;

void fe_complete(void* h, long long batch_id, const uint8_t* granted,
                 const double* remaining) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(batch_id);
  if (it == sh->inflight.end()) return;
  uint64_t t = now_ns();
  uint64_t t_flush = it->second.t_flush_ns;
  double exec_s = double(t - t_flush) * 1e-9;
  bool t0_on = sh->owner->t0_enabled.load(std::memory_order_relaxed);
  size_t i = 0;
  for (const Item& item : it->second.items) {
    if (granted[i] == kRowSkip) {
      i++;
      continue;
    }
    std::string resp =
        encode_decision(item.seq, granted[i] != 0, remaining[i]);
    auto itc = sh->conns.find(item.conn_id);
    if (itc != sh->conns.end()) {
      send_to_conn(sh, itc->second, resp.data(), resp.size());
    }
    if (t0_on && item.op == OP_ACQUIRE && granted[i] != 0) {
      // Every granted fall-through decision is an authoritative balance
      // observation: seed/refresh the key's tier-0 replica (in its
      // OWNER partition) from it — sized for the grant's token cost
      // (see t0_install).
      t0_install(t0_slice(sh), item.key, item.a, item.b, remaining[i],
                 t, double(item.count));
    }
    hist_record(sh, double(t - item.t_ns) * 1e-9);
    stage_record(sh, 0, double(t_flush - item.t_ns) * 1e-9);  // queue
    stage_record(sh, 1, exec_s);  // Python dispatch + store + kernel
    sh->requests_served++;
    i++;
  }
  sh->inflight.erase(it);
  maybe_flush_after_complete(sh);
  if (sh->uring) uring_submit(sh);  // one enter for the whole batch
}

// Fail a batch (store raised): every item gets a routable error reply.
void fe_fail(void* h, long long batch_id, const char* msg) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->inflight.find(batch_id);
  if (it == sh->inflight.end()) return;
  uint64_t t = now_ns();
  uint64_t t_flush = it->second.t_flush_ns;
  double exec_s = double(t - t_flush) * 1e-9;
  for (const Item& item : it->second.items) {
    std::string resp = encode_error(item.seq, msg);
    auto itc = sh->conns.find(item.conn_id);
    if (itc != sh->conns.end()) {
      send_to_conn(sh, itc->second, resp.data(), resp.size());
    }
    hist_record(sh, double(t - item.t_ns) * 1e-9);
    stage_record(sh, 0, double(t_flush - item.t_ns) * 1e-9);
    stage_record(sh, 1, exec_s);
    sh->requests_served++;
  }
  sh->inflight.erase(it);
  maybe_flush_after_complete(sh);
  if (sh->uring) uring_submit(sh);
}

long long fe_pt_conn(void* h) {
  return (long long)shard_of(h)->cur_pt.conn_id;
}

int fe_pt_len(void* h) { return int(shard_of(h)->cur_pt.frame.size()); }

void fe_pt_copy(void* h, char* buf) {
  Shard* sh = shard_of(h);
  std::memcpy(buf, sh->cur_pt.frame.data(), sh->cur_pt.frame.size());
}

// Feature probe: this binary's fe_complete honors the kRowSkip
// sentinel. Python falls back to deny-only gating without it (a stale
// .so must never read the sentinel as "granted").
int fe_has_row_skip(void) { return 1; }

// Send a pre-encoded reply frame (passthrough responses).
void fe_send(void* h, uint64_t conn_id, const char* data, int len) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto itc = sh->conns.find(conn_id);
  if (itc == sh->conns.end()) return;
  send_to_conn(sh, itc->second, data, size_t(len));
  sh->requests_served++;
  if (sh->uring) uring_submit(sh);
}

void fe_set_authed(void* h, uint64_t conn_id, int authed) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto itc = sh->conns.find(conn_id);
  if (itc == sh->conns.end()) return;
  Conn* c = itc->second;
  c->auth_pending = false;
  c->authed = authed != 0;
  if (!c->authed) return;  // failure path: Python sends the error and
                           // closes via fe_close_conn; held frames die
                           // with the connection
  // Replay frames the client pipelined behind its HELLO, in order.
  std::vector<std::string> held = std::move(c->held);
  c->held.clear();
  c->held_bytes = 0;
  bool ok = true;
  for (const std::string& f : held) {
    if (!handle_frame(sh, c,
                      reinterpret_cast<const uint8_t*>(f.data()),
                      f.size())) {
      ok = false;
      break;
    }
  }
  if (!ok) {
    if (!c->out.empty()) {
      c->closing = true;  // drain the error reply first
      flush_out(sh, c);
    } else {
      close_conn(sh, c);
    }
  } else {
    flush_queued(sh, c);  // replayed tier-0/PING replies
  }
  if (sh->uring) uring_submit(sh);
  // Replayed hot items joined `pending` from this (loop) thread: wake
  // the IO thread so its flush/deadline evaluation sees them.
  wake_io(sh);
}

void fe_close_conn(void* h, uint64_t conn_id) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto itc = sh->conns.find(conn_id);
  if (itc == sh->conns.end()) return;
  Conn* c = itc->second;
  if (c->out.empty() && (!sh->uring || (c->wbuf_off >= c->wbuf.size() &&
                                        !c->send_inflight))) {
    close_conn(sh, c);
  } else {
    c->closing = true;  // drain the goodbye (e.g. auth-failed error) first
    if (sh->uring) uring_arm_send(sh, c);
  }
  if (sh->uring) uring_submit(sh);
}

// Whole-node counters with a Frontend handle (the sum across shards);
// one shard's slice with a shard handle — the OP_STATS shards=[...]
// breakdown.
void fe_counts(void* h, long long* requests, long long* connections,
               long long* batches) {
  *requests = *connections = *batches = 0;
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    *requests += sh->requests_served;
    *connections += sh->connections_served;
    *batches += sh->batches_flushed;
  }
}

long long fe_hist(void* h, uint64_t* counts) {
  std::memset(counts, 0, sizeof(uint64_t) * kHistBuckets);
  long long total = 0;
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    for (int b = 0; b < kHistBuckets; b++) counts[b] += sh->hist[b];
    total += sh->hist_total;
  }
  return total;
}

// Per-stage latency histograms (same 82-bucket convention as fe_hist).
// stage: 0 = serving (arrival -> completion, the fe_hist span), 1 =
// queue (arrival -> batch cut), 2 = exec (batch cut -> completion).
// Sums across shards for a Frontend handle (log-bucket histograms are
// closed under addition, so merged quantiles read identically to a
// single shard's). Copies bucket counts into `counts`, writes the
// running sum of seconds into `sum_out`, returns the sample total.
// Unknown stage returns -1.
long long fe_stage_hist(void* h, int stage, uint64_t* counts,
                        double* sum_out) {
  if (stage != 0 && (stage - 1 < 0 || stage - 1 >= Shard::kStages)) {
    return -1;
  }
  std::memset(counts, 0, sizeof(uint64_t) * kHistBuckets);
  *sum_out = 0.0;
  long long total = 0;
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    if (stage == 0) {
      for (int b = 0; b < kHistBuckets; b++) counts[b] += sh->hist[b];
      *sum_out += sh->hist_sum;
      total += sh->hist_total;
    } else {
      int s = stage - 1;
      for (int b = 0; b < kHistBuckets; b++) {
        counts[b] += sh->stage_hist[s][b];
      }
      *sum_out += sh->stage_sum[s];
      total += sh->stage_total[s];
    }
  }
  return total;
}

void fe_hist_reset(void* h) {
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    std::memset(sh->hist, 0, sizeof sh->hist);
    sh->hist_total = 0;
    sh->hist_sum = 0.0;
    std::memset(sh->stage_hist, 0, sizeof sh->stage_hist);
    std::memset(sh->stage_total, 0, sizeof sh->stage_total);
    for (int s = 0; s < Shard::kStages; s++) sh->stage_sum[s] = 0.0;
  }
}

void fe_stop(void* h) {
  Frontend* fe = owner_of(h);
  fe->stopping.store(true);
  for (Shard* sh : fe->shards) {
    wake_io(sh);
    {
      std::lock_guard<FeMutex> lk(sh->mu);
      sh->cv.notify_all();
    }
    if (sh->io.joinable()) sh->io.join();
    if (sh->ring != nullptr) {
      // After the join no op can complete into shard memory; closing
      // the ring fd also drops the registered-file references.
      uring_free_ring(sh->ring);
      sh->ring = nullptr;
      sh->uring = false;
    }
    ::close(sh->listen_fd);
    ::close(sh->epfd);
    ::close(sh->evfd);
    ::close(sh->tfd);
  }
}

void fe_free(void* h) {
  Frontend* fe = owner_of(h);
  for (Shard* sh : fe->shards) {
    if (sh->ring != nullptr) uring_free_ring(sh->ring);  // stop-less free
    delete sh;
  }
  for (T0Part* part : fe->t0parts) delete part;
  delete fe;
}

// ---------------------------------------------------------------------
// io_uring transport ABI (round 16). Feature detection mirrors the
// shard ABI's: utils/native.py probes these symbols and falls back to
// fe_start_sharded (epoll or env-resolved) when they are absent.
// ---------------------------------------------------------------------

// Process-wide availability: 1 when the kernel offers the 5.19+ feature
// level this transport needs AND no env/sanitizer gate forbids it.
int fe_uring_available(void) {
  std::string r;
  return uring_probe(&r);
}

// Availability plus the human-readable reason (for `--probe` output and
// the loud fallback log line). Returns the same 0/1 as above; writes a
// NUL-terminated reason (empty on success) into buf.
int fe_uring_probe(char* buf, int len) {
  std::string r;
  int ok = uring_probe(&r);
  if (ok != 0 && r.empty()) {
    r = "io_uring available (5.19+ feature level)";
  }
  if (buf != nullptr && len > 0) {
    size_t n = std::min(size_t(len - 1), r.size());
    std::memcpy(buf, r.data(), n);
    buf[n] = '\0';
  }
  return ok;
}

// How many of the node's shards are actually serving on uring (the
// request is per-node; refusal is per-shard).
int fe_uring_shards(void* h) {
  int n = 0;
  for (Shard* sh : owner_of(h)->shards) n += sh->uring ? 1 : 0;
  return n;
}

// Per-shard transport status: returns 1 (uring) / 0 (epoll) / -1 (bad
// index) and writes the shard's fallback reason (empty when it never
// fell back) into buf.
int fe_uring_reason(void* h, int shard, char* buf, int len) {
  Frontend* fe = owner_of(h);
  if (shard < 0 || shard >= fe->nshards) return -1;
  Shard* sh = fe->shards[size_t(shard)];
  if (buf != nullptr && len > 0) {
    size_t n = std::min(size_t(len - 1), sh->uring_reason.size());
    std::memcpy(buf, sh->uring_reason.data(), n);
    buf[n] = '\0';
  }
  return sh->uring ? 1 : 0;
}

// out[8]: shards on uring, shards on SQPOLL, io_uring_enter calls,
// SQEs submitted, CQEs completed, data-plane syscalls (both
// transports — the syscalls/frame numerator), shards that fell back
// after an explicit uring request, reserved. Frontend OR shard handle.
void fe_uring_counts(void* h, long long* out) {
  for (int i = 0; i < 8; i++) out[i] = 0;
  for (Shard* sh : shards_of(h)) {
    if (sh->uring) out[0]++;
    if (sh->uring_sqpoll) out[1]++;
    if (sh->ring != nullptr) {
      out[2] += sh->ring->enters.load(std::memory_order_relaxed);
      out[3] += sh->ring->sqes_submitted.load(std::memory_order_relaxed);
      out[4] += sh->ring->cqes_seen.load(std::memory_order_relaxed);
    }
    out[5] += sh->io_syscalls.load(std::memory_order_relaxed);
    // A fallback is any shard serving epoll WITH a recorded reason —
    // that covers both probe/setup refusals and the DRL_TPU_NO_URING
    // coercion (which rewrites fe->uring_mode, so the mode alone can't
    // tell). uring_reason is written once before the IO threads start.
    if (!sh->uring && !sh->uring_reason.empty()) out[6]++;
  }
}

// ---------------------------------------------------------------------
// Tier-0 admission cache ABI (see the T0Part block above). The table is
// partitioned by key hash across the shards; all calls below take
// partition mutexes only (never a shard's connection mutex), and the
// harvest/ack pair is driven by the ONE Python sync pump
// (runtime/native_frontend.py _t0_sync_loop) regardless of shard count
// — a single reconciliation stream, a single epsilon envelope.
// ---------------------------------------------------------------------

// Enable tier-0 with a bounded replica table. `slots` sizes EACH
// shard's slice (rounded up to a power of two): any shard can see any
// key, so every slice needs full-keyspace capacity — table memory is
// nshards × slots × (entry + key). Budgets are divided by the shard
// count inside t0_budget_of, so the summed per-shard headroom stays
// inside the flat single-shard envelope (see T0Part). Returns the
// total slot count actually allocated (the Python pump sizes harvest
// buffers from it — a harvest can return one row per shard per key).
int fe_t0_configure(void* h, int slots, double fraction, double min_budget,
                    double max_budget, int stale_ms, int ttl_ms) {
  Frontend* fe = owner_of(h);
  size_t want = size_t(slots > 0 ? slots : 4096);
  size_t per = 1;
  while (per < want) per <<= 1;
  T0Config cfg;
  cfg.mask = per - 1;
  cfg.split = double(fe->nshards);
  cfg.fraction = fraction > 0 ? fraction : 0.5;
  cfg.min_budget = min_budget > 0 ? min_budget : 1.0;
  cfg.max_budget = max_budget > 0 ? max_budget : 1048576.0;
  cfg.stale_ns = uint64_t(stale_ms > 0 ? stale_ms : 1000) * 1000000ull;
  cfg.ttl_ns = uint64_t(ttl_ms > 0 ? ttl_ms : 30000) * 1000000ull;
  for (T0Part* part : fe->t0parts) {
    std::lock_guard<T0SpinMutex> lk(part->mu);
    part->cfg = cfg;
    part->tab.assign(per, T0Entry{});
    part->scan = 0;
  }
  fe->t0_enabled.store(true, std::memory_order_release);
  return int(per * size_t(fe->nshards));
}

// Drain accumulated local grants: copies up to max_n (key, amount, cap,
// rate) rows out (key_blob concatenated, klens delimiting) and zeroes
// each entry's pending. Entries that do not fit stay pending for the
// next round — partitions rotate and each partition's scan resumes
// from its own cursor, so an overflowing round cannot starve either a
// partition or the tail of one partition's table. Idle pending-free
// entries are TTL-evicted in the same pass. Returns the row count.
int fe_t0_harvest(void* h, char* key_blob, int blob_cap, int32_t* klens,
                  double* amounts, double* caps, double* rates, int max_n) {
  Frontend* fe = owner_of(h);
  std::vector<T0Part*> parts = t0parts_of(h);
  if (parts.empty()) return 0;
  uint64_t now = now_ns();
  size_t nparts = parts.size();
  bool rotate = as_frontend(h) != nullptr && nparts > 1;
  size_t start = rotate ? fe->harvest_part % nparts : 0;
  int n = 0;
  size_t off = 0;
  bool full = false;
  for (size_t pi = 0; pi < nparts && !full; pi++) {
    T0Part* part = parts[(start + pi) % nparts];
    std::lock_guard<T0SpinMutex> lk(part->mu);
    size_t total = part->tab.size();
    if (total == 0) continue;
    size_t i = part->scan;
    for (size_t scanned = 0; scanned < total; scanned++, i++) {
      T0Entry& e = part->tab[i % total];
      if (!e.live) continue;
      if (e.pending > 0.0) {
        if (n >= max_n || off + e.key.size() > size_t(blob_cap)) {
          full = true;
          break;
        }
        std::memcpy(key_blob + off, e.key.data(), e.key.size());
        off += e.key.size();
        klens[n] = int32_t(e.key.size());
        amounts[n] = e.pending;
        caps[n] = e.cap;
        rates[n] = e.rate;
        e.pending = 0.0;
        n++;
      } else if (now - e.last_touch_ns > part->cfg.ttl_ns) {
        e.live = false;
        part->evictions++;
      }
    }
    part->scan = i % total;
    if (full && rotate) fe->harvest_part = (start + pi) % nparts;
  }
  if (!full && rotate) fe->harvest_part = (start + 1) % nparts;
  return n;
}

// Complete a sync round: install the fresh authoritative balance into
// EVERY shard's replica of each harvested key (the Python pump merges
// per-shard harvest rows by key before the debit, so each key is
// acked once with the one store balance) and recompute the per-shard
// budget shares. Grants made after the harvest (still in `pending`)
// remain outstanding against the new envelope; the drained portion is
// reflected in the balance itself.
void fe_t0_ack(void* h, const char* key_blob, const int32_t* klens,
               const double* caps, const double* rates,
               const double* remainings, int n) {
  std::vector<T0Part*> parts = t0parts_of(h);
  if (parts.empty()) return;
  uint64_t now = now_ns();
  for (T0Part* part : parts) {
    std::lock_guard<T0SpinMutex> lk(part->mu);
    size_t off = 0;
    for (int i = 0; i < n; i++) {
      std::string_view key(key_blob + off, size_t(klens[i]));
      off += size_t(klens[i]);
      T0Entry* e = t0_find(part, key, t0_hash(key), caps[i], rates[i]);
      if (e == nullptr) continue;  // not hosted here / evicted mid-sync
      e->last_remaining = remainings[i];
      e->admitted = e->pending;
      e->budget = t0_budget_of(
          part->cfg, std::max(remainings[i] - e->admitted, 0.0));
      e->last_ack_ns = now;
      e->last_touch_ns = now;
    }
  }
}

// Live config mutation (round 7): kill every replica of one retired
// (cap, rate) config and hand back its un-harvested local grants —
// [key_blob/klens/amounts rows, like fe_t0_harvest] — so the sync pump
// debits them through the REPLACEMENT config. Round 11: the sweep fans
// out to EVERY partition under ONE combined critical section — all
// partition locks are taken up front (index order; this is the only
// multi-partition lock site, so there is no ordering partner to
// deadlock with — and that is now a CHECKED contract, not a comment:
// drl-verify's lock-order analyzer (tools/drl_verify/lockorder.py,
// rule slice-sweep-order) fails `make check` on a reversed sweep, a
// second multi-slice section, or any nested same-class acquisition
// outside this one). A config retired on shard 0 but still live on shard
// 3 would be a double-admit window; with the combined section no grant
// can land on ANY partition between the harvest and the kill. Without
// the kill, stale frames would keep being admitted (or confidently
// denied) against a table nobody serves from anymore; dead entries
// make them fall through to the batch lane's routable "config moved"
// error. Returns the number of rows written (entries with pending >
// 0); every matching entry is dead on return regardless.
int fe_t0_retire(void* h, double cap, double rate, char* key_blob,
                 int blob_cap, int32_t* klens, double* amounts,
                 int max_keys) {
  std::vector<T0Part*> parts = t0parts_of(h);
  std::vector<std::unique_lock<T0SpinMutex>> locks;
  locks.reserve(parts.size());
  for (T0Part* part : parts) locks.emplace_back(part->mu);
  int n = 0;
  int off = 0;
  for (T0Part* part : parts) {
    for (T0Entry& e : part->tab) {
      if (!e.live || e.cap != cap || e.rate != rate) continue;
      if (e.pending > 0.0 && n < max_keys &&
          off + int(e.key.size()) <= blob_cap) {
        std::memcpy(key_blob + off, e.key.data(), e.key.size());
        klens[n] = int32_t(e.key.size());
        amounts[n] = e.pending;
        off += int(e.key.size());
        n++;
      }
      e.live = false;
      e.pending = 0.0;
      part->evictions++;
    }
  }
  return n;
}

// out[6]: hits, local denies, misses, installs, evictions, live
// entries. Frontend handle = summed across partitions (the whole-node
// gauges); shard handle = that shard's own partition.
void fe_t0_counts(void* h, long long* out) {
  for (int i = 0; i < 6; i++) out[i] = 0;
  for (T0Part* part : t0parts_of(h)) {
    std::lock_guard<T0SpinMutex> lk(part->mu);
    long long live = 0;
    for (const T0Entry& e : part->tab) live += e.live ? 1 : 0;
    out[0] += part->hits;
    out[1] += part->local_denies;
    out[2] += part->misses;
    out[3] += part->installs;
    out[4] += part->evictions;
    out[5] += live;
  }
}

// Per-slice ε-consumption counters (round 18, the conservation audit
// plane): out[i] = cumulative tokens granted locally by slice i.
// Frontend handle = every shard's slice in shard order (the whole-node
// per-slice breakdown); shard handle = that shard's own slice only.
// Returns the number of slices written (≤ max_parts). A separate
// export rather than a widened fe_t0_counts: stale Python halves keep
// passing 6-element arrays to fe_t0_counts, and the binding layer
// feature-detects this symbol exactly like fe_t0_retire.
int fe_t0_eps(void* h, double* out, int max_parts) {
  int n = 0;
  for (T0Part* part : t0parts_of(h)) {
    if (n >= max_parts) break;
    std::lock_guard<T0SpinMutex> lk(part->mu);
    out[n++] = part->grant_tokens;
  }
  return n;
}

// ---------------------------------------------------------------------
// Native bulk lane ABI (round 8). fe_bulk_configure arms it (default
// off so a new binary under an older pump keeps the round-7
// passthrough behavior); fe_wait returns 3 when a residue job is
// ready; fe_bulk_meta / fe_bulk_ptrs expose the CURRENT job (same
// call-window contract as fe_batch_*: between fe_wait returning 3 and
// the matching complete/discard/fail); fe_bulk_complete merges
// Python's residue verdicts, encodes RESP_BULK, and answers the
// client. The ptrs stay valid until the job is erased — Python's
// KeyBlob views read them in place (zero copy, zero UTF-8 decode).
// Jobs are per-shard state: the pump thread that pulled the job from
// fe_wait completes it against the same shard handle.
// ---------------------------------------------------------------------

// Arm/disarm the lane on every shard of the handle — one call, all
// shards, so a frame arriving on shard 3 mid-configure can at worst
// see the OLD whole-lane mode, never a half-armed mix on its own
// shard.
int fe_bulk_configure(void* h, int enable, int t0_rows, int hot_feed) {
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    sh->bulk_native = enable != 0;
    sh->bulk_t0 = t0_rows != 0;
    sh->bulk_hot = hot_feed != 0;
  }
  return 1;
}

long long fe_bulk_id(void* h) { return shard_of(h)->cur_bulk_id; }

// u[11]: job id, conn id, seq, flags, n, blob bytes, residue rows,
// trace hi/lo/parent, trace flags. f[2]: a, b. Job id 0 = no job.
void fe_bulk_meta(void* h, unsigned long long* u, double* f) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->bulk_inflight.find(sh->cur_bulk_id);
  if (it == sh->bulk_inflight.end()) {
    u[0] = 0;
    return;
  }
  const BulkJob& j = it->second;
  u[0] = (unsigned long long)j.id;
  u[1] = j.conn_id;
  u[2] = j.seq;
  u[3] = j.flags;
  u[4] = j.n;
  u[5] = j.blob.size();
  u[6] = j.residue.size();
  u[7] = j.tr_hi;
  u[8] = j.tr_lo;
  u[9] = j.tr_parent;
  u[10] = j.tr_flags;
  f[0] = j.a;
  f[1] = j.b;
}

// ptrs[4]: key blob, offsets (i64[n+1]), counts (i64[n]), residue
// (i32[residue_n]) — addresses into the job, stable until it is erased.
void fe_bulk_ptrs(void* h, unsigned long long* ptrs) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->bulk_inflight.find(sh->cur_bulk_id);
  if (it == sh->bulk_inflight.end()) {
    ptrs[0] = ptrs[1] = ptrs[2] = ptrs[3] = 0;
    return;
  }
  BulkJob& j = it->second;
  ptrs[0] = (unsigned long long)(uintptr_t)j.blob.data();
  ptrs[1] = (unsigned long long)(uintptr_t)j.offsets.data();
  ptrs[2] = (unsigned long long)(uintptr_t)j.counts.data();
  ptrs[3] = (unsigned long long)(uintptr_t)j.residue.data();
}

// Merge Python's residue verdicts (granted/remaining indexed in
// `residue` order), install replicas from granted fall-through rows
// (the bulk lane's mirror of fe_complete's scalar install), encode the
// RESP_BULK reply, and answer the client.
void fe_bulk_complete(void* h, long long job_id, const uint8_t* granted,
                      const double* remaining) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->bulk_inflight.find(job_id);
  if (it == sh->bulk_inflight.end()) return;
  BulkJob& job = it->second;
  uint64_t t = now_ns();
  bool t0_on = sh->owner->t0_enabled.load(std::memory_order_relaxed);
  for (size_t r = 0; r < job.residue.size(); r++) {
    size_t i = size_t(job.residue[r]);
    job.verdict[i] = granted[r] ? 1 : 0;
    job.remaining[i] = float(remaining[r]);
    if (t0_on && sh->bulk_t0 && job.kind == BULK_KIND_BUCKET &&
        granted[r] && job.with_remaining && job.counts[i] > 0) {
      size_t klen = size_t(job.offsets[i + 1] - job.offsets[i]);
      if (klen <= kT0MaxKey) {
        t0_install(t0_slice(sh),
                   std::string(job.blob.data() + job.offsets[i], klen),
                   job.a, job.b, remaining[r], t,
                   double(job.counts[i]));
      }
    }
  }
  std::string resp = encode_bulk_reply(job.seq, job.with_remaining,
                                       job.n, job.verdict.data(),
                                       job.remaining.data());
  auto itc = sh->conns.find(job.conn_id);
  if (itc != sh->conns.end()) {
    send_to_conn(sh, itc->second, resp.data(), resp.size());
  }
  if (job.tr_flags & 1) {
    bool all = true;
    for (uint32_t i = 0; i < job.n; i++) all = all && job.verdict[i] == 1;
    trace_ring_push_raw(sh, job.tr_hi, job.tr_lo, job.tr_parent,
                        job.tr_flags, OP_ACQUIRE_MANY, all, job.t_ns, t);
  }
  hist_record(sh, double(t - job.t_ns) * 1e-9);
  sh->requests_served++;
  finish_bulk_job(sh, job_id);
  if (sh->uring) uring_submit(sh);
}

// Drop a job whose frame Python already answered wholesale via fe_send
// (frame-level gate errors / drain envelope — the kRowSkip posture,
// whole-frame edition). fe_send counted the request; this only records
// latency and un-parks chained successors.
void fe_bulk_discard(void* h, long long job_id) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->bulk_inflight.find(job_id);
  if (it == sh->bulk_inflight.end()) return;
  hist_record(sh, double(now_ns() - it->second.t_ns) * 1e-9);
  finish_bulk_job(sh, job_id);
  if (sh->uring) uring_submit(sh);
}

// Fail a job (store raised): the frame gets one routable error reply.
void fe_bulk_fail(void* h, long long job_id, const char* msg) {
  Shard* sh = shard_of(h);
  std::lock_guard<FeMutex> lk(sh->mu);
  auto it = sh->bulk_inflight.find(job_id);
  if (it == sh->bulk_inflight.end()) return;
  BulkJob& job = it->second;
  std::string resp = encode_error(job.seq, msg);
  auto itc = sh->conns.find(job.conn_id);
  if (itc != sh->conns.end()) {
    send_to_conn(sh, itc->second, resp.data(), resp.size());
  }
  hist_record(sh, double(now_ns() - job.t_ns) * 1e-9);
  sh->requests_served++;
  finish_bulk_job(sh, job_id);
  if (sh->uring) uring_submit(sh);
}

// out[7]: frames, frames decided fully in C, rows, rows decided
// locally (tier-0 grant/deny), residue rows, locally granted permits
// (the amount the sync pump debits), hot-ring drops. Frontend handle =
// summed across shards; shard handle = that shard's slice.
void fe_bulk_counts(void* h, long long* out) {
  for (int i = 0; i < 7; i++) out[i] = 0;
  for (Shard* sh : shards_of(h)) {
    std::lock_guard<FeMutex> lk(sh->mu);
    out[0] += sh->bulk_frames;
    out[1] += sh->bulk_frames_local;
    out[2] += sh->bulk_rows;
    out[3] += sh->bulk_rows_local;
    out[4] += sh->bulk_rows_residue;
    out[5] += (long long)sh->bulk_permits_local;
    out[6] += sh->hot_dropped;
  }
}

// Drain up to max_n aggregated (key, weight) hot-key rows from the
// bulk lanes' rings (key_blob concatenated, klens delimiting) — the
// pump offers them to the heavy-hitter sketch. Each shard keeps its
// own ring; the ONE harvest pump drains them all (rotating), so the
// sketch — and therefore split_hot_keys — still sees whole-node ranks.
// Returns the row count.
int fe_hot_harvest(void* h, char* key_blob, int blob_cap, int32_t* klens,
                   double* weights, int max_n) {
  Frontend* fe = owner_of(h);
  std::vector<Shard*> shards = shards_of(h);
  size_t nsh = shards.size();
  bool rotate = as_frontend(h) != nullptr && nsh > 1;
  size_t start = rotate ? fe->hot_shard % nsh : 0;
  int n = 0;
  int off = 0;
  bool full = false;
  for (size_t si = 0; si < nsh && !full; si++) {
    Shard* sh = shards[(start + si) % nsh];
    std::lock_guard<FeMutex> lk(sh->mu);
    while (!sh->hot_ring.empty()) {
      const auto& front = sh->hot_ring.front();
      if (n >= max_n || off + int(front.first.size()) > blob_cap) {
        full = true;
        break;
      }
      std::memcpy(key_blob + off, front.first.data(), front.first.size());
      klens[n] = int32_t(front.first.size());
      weights[n] = front.second;
      off += int(front.first.size());
      n++;
      sh->hot_ring.pop_front();
    }
    if (full && rotate) fe->hot_shard = (start + si) % nsh;
  }
  if (!full && rotate) fe->hot_shard = (start + 1) % nsh;
  return n;
}

// ---------------------------------------------------------------------
// Native closed-loop load generator: the measurement client for the
// front-end (a Python client's own ~14µs/request scheduling floor would
// bound the measurement, not the server — benchmarks/RESULTS.md
// "Per-request socket ceiling"). Opens `conns` connections, keeps
// `depth` ACQUIRE requests in flight on each, counts grants. Single
// epoll thread; returns total replies, grants, and elapsed seconds.
// ---------------------------------------------------------------------

namespace {

struct LgConn {
  int fd;
  int sent = 0, recvd = 0;
  bool dead = false;
  std::vector<uint8_t> in;
  size_t in_off = 0;
};

std::string lg_request(uint32_t seq, uint8_t op, const std::string& key,
                       double a, double b) {
  std::string s;
  uint16_t klen = uint16_t(key.size());
  wr_u32(&s, uint32_t(kBodyOff + 2 + klen + 20));
  s.push_back(char(kVersion));
  wr_u32(&s, seq);
  s.push_back(char(op));
  s.append(reinterpret_cast<const char*>(&klen), 2);
  s.append(key);
  int32_t count = 1;
  s.append(reinterpret_cast<const char*>(&count), 4);
  wr_f64(&s, a);
  wr_f64(&s, b);
  return s;
}

}  // namespace

int fe_loadgen(const char* host, int port, int n_conns, int depth,
               int reqs_per_conn, int keyspace, double a, double b,
               int op, double* out_elapsed_s, long long* out_replies,
               long long* out_granted) {
  uint8_t op8 = uint8_t(op > 0 ? op : OP_ACQUIRE);
  std::vector<LgConn> conns{size_t(n_conns)};
  int epfd = epoll_create1(0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(epfd);
    return -1;
  }
  for (int i = 0; i < n_conns; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
      ::close(epfd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    set_nonblock(fd);
    conns[size_t(i)].fd = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = uint32_t(i);
    epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
  }
  long long replies = 0, granted = 0;
  int live = n_conns;
  const long long want = (long long)n_conns * reqs_per_conn;
  uint64_t t0 = now_ns();
  // Prime: `depth` pipelined requests per connection.
  for (int i = 0; i < n_conns; i++) {
    std::string burst;
    for (int d = 0; d < depth && d < reqs_per_conn; d++) {
      std::string key =
          "lg" + std::to_string(i) + "-" + std::to_string(d % keyspace);
      burst += lg_request(uint32_t(conns[size_t(i)].sent++), op8, key, a, b);
    }
    ssize_t r = ::send(conns[size_t(i)].fd, burst.data(), burst.size(),
                       MSG_NOSIGNAL);
    (void)r;  // pipelined burst fits the socket buffer at these depths
  }
  epoll_event events[64];
  while (replies < want && live > 0) {
    int n = epoll_wait(epfd, events, 64, 10000);
    if (n <= 0) break;  // stalled server: bail with what we have
    for (int e = 0; e < n; e++) {
      LgConn& c = conns[events[e].data.u32];
      if (c.dead) continue;
      uint8_t buf[65536];
      for (;;) {
        ssize_t r = ::recv(c.fd, buf, sizeof buf, 0);
        if (r > 0) {
          c.in.insert(c.in.end(), buf, buf + r);
          continue;
        }
        if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK)) {
          // EOF/reset (e.g. an auth-protected server closing us): a
          // level-triggered dead fd would spin epoll_wait forever —
          // deregister and count the conn out instead.
          epoll_ctl(epfd, EPOLL_CTL_DEL, c.fd, nullptr);
          c.dead = true;
          live--;
        }
        break;
      }
      int completed = 0;
      for (;;) {
        size_t avail = c.in.size() - c.in_off;
        if (avail < 4) break;
        uint32_t len = rd_u32(c.in.data() + c.in_off);
        if (avail < 4 + size_t(len)) break;
        const uint8_t* body = c.in.data() + c.in_off + 4;
        // Length check FIRST: body[5]/body[6] on a short frame (len < 7)
        // would read past the buffered input.
        if (len >= kBodyOff + 1 && body[5] == RESP_DECISION && body[6]) {
          granted++;
        }
        c.in_off += 4 + len;
        replies++;
        c.recvd++;
        completed++;
      }
      if (c.in_off == c.in.size()) {
        c.in.clear();
        c.in_off = 0;
      }
      // Refill the pipeline: one new request per completed reply.
      if (completed > 0 && c.sent < reqs_per_conn) {
        std::string burst;
        for (int d = 0; d < completed && c.sent < reqs_per_conn; d++) {
          std::string key = "lg" + std::to_string(events[e].data.u32) + "-" +
                            std::to_string(c.sent % keyspace);
          burst += lg_request(uint32_t(c.sent++), op8, key, a, b);
        }
        ssize_t r = ::send(c.fd, burst.data(), burst.size(), MSG_NOSIGNAL);
        (void)r;
      }
    }
  }
  *out_elapsed_s = double(now_ns() - t0) * 1e-9;
  *out_replies = replies;
  *out_granted = granted;
  for (auto& c : conns) ::close(c.fd);
  ::close(epfd);
  return 0;
}

// Bulk-lane measurement client (round 11): `conns` connections each
// keeping `depth` OP_ACQUIRE_MANY frames of `rows_per_frame` rows in
// flight. The scalar fe_loadgen exists because a Python client's
// ~14µs/request floor would bound the measurement; at multi-shard bulk
// rates even a Python PER-FRAME client bounds the node (one encode +
// event-loop turn per 4096 rows × N shards), so the shard-sweep rig
// needs frames built and counted in C too. Keys draw from one shared
// `keyspace` pool ("b<i>") — the hot tier-0 shape the sweep measures —
// and the kernel's SO_REUSEPORT hash spreads the connections across
// shards. Frames carry the with-remaining flag: the bulk lane only
// installs tier-0 replicas from with-remaining grants, and the sweep
// exists to measure the replicated-envelope hot path, not the residue
// lane. Returns total frames, rows, and granted rows (bitmap popcount
// — the bitmap precedes the f32 remaining array in RESP_BULK).
int fe_lg_bulk(const char* host, int port, int n_conns, int depth,
               int frames_per_conn, int rows_per_frame, int keyspace,
               double a, double b, double* out_elapsed_s,
               long long* out_frames, long long* out_rows,
               long long* out_granted) {
  if (n_conns <= 0 || rows_per_frame <= 0 || keyspace <= 0) return -1;
  std::vector<LgConn> conns{size_t(n_conns)};
  int epfd = epoll_create1(0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(epfd);
    return -1;
  }
  for (int i = 0; i < n_conns; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
      ::close(epfd);
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    set_nonblock(fd);
    conns[size_t(i)].fd = fd;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u32 = uint32_t(i);
    epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
  }
  // Outbound staging per connection: frames queue in LgConn-local
  // buffers and drain via EPOLLOUT — a burst past the socket buffer
  // must NOT busy-spin on EAGAIN (16 client threads spinning is a
  // measurable bite out of the very CPUs the server under test needs).
  std::vector<std::string> outq(static_cast<size_t>(n_conns));
  std::vector<size_t> outq_off(static_cast<size_t>(n_conns), 0);
  std::vector<uint8_t> want_out(static_cast<size_t>(n_conns), 0);
  // One frame template per sequence slot: the body is identical for
  // every send except the seq, so build it once and patch seq in place.
  uint64_t n = uint64_t(rows_per_frame);
  std::string body;
  body.push_back(char(kVersion));
  wr_u32(&body, 0);  // seq, patched per send at offset 1
  body.push_back(char(OP_ACQUIRE_MANY));
  body.push_back(char(kBulkFlagRemaining));  // kind bucket, remainings on
  wr_f64(&body, a);
  wr_f64(&body, b);
  wr_u32(&body, uint32_t(n));
  std::string blob;
  std::vector<uint16_t> klens(n);
  for (uint64_t i = 0; i < n; i++) {
    std::string key = "b" + std::to_string(i % uint64_t(keyspace));
    klens[i] = uint16_t(key.size());
    blob += key;
  }
  body.append(reinterpret_cast<const char*>(klens.data()), 2 * n);
  body += blob;
  for (uint64_t i = 0; i < n; i++) wr_u32(&body, 1);  // unit counts
  std::string frame;
  wr_u32(&frame, uint32_t(body.size()));
  frame += body;
  constexpr size_t kSeqOff = 5;  // [u32 len][u8 ver] then seq
  auto flush_conn = [&](size_t ci) {
    LgConn& c = conns[ci];
    std::string& out = outq[ci];
    size_t& off = outq_off[ci];
    while (off < out.size()) {
      ssize_t r = ::send(c.fd, out.data() + off, out.size() - off,
                         MSG_NOSIGNAL);
      if (r > 0) {
        off += size_t(r);
        continue;
      }
      if (r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (!want_out[ci]) {
          want_out[ci] = 1;
          epoll_event ev{};
          ev.events = EPOLLIN | EPOLLOUT;
          ev.data.u32 = uint32_t(ci);
          epoll_ctl(epfd, EPOLL_CTL_MOD, c.fd, &ev);
        }
        return;
      }
      break;  // hard error: reader side will reap the conn
    }
    out.clear();
    off = 0;
    if (want_out[ci]) {
      want_out[ci] = 0;
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.u32 = uint32_t(ci);
      epoll_ctl(epfd, EPOLL_CTL_MOD, c.fd, &ev);
    }
  };
  auto send_frames = [&](size_t ci, int count) {
    LgConn& c = conns[ci];
    for (int d = 0; d < count && c.sent < frames_per_conn; d++) {
      uint32_t seq = uint32_t(c.sent++);
      std::memcpy(&frame[kSeqOff], &seq, 4);
      outq[ci] += frame;
    }
    flush_conn(ci);
  };
  long long frames_done = 0, granted = 0;
  int live = n_conns;
  const long long want = (long long)n_conns * frames_per_conn;
  uint64_t t0 = now_ns();
  for (size_t ci = 0; ci < size_t(n_conns); ci++) {
    send_frames(ci, depth);
  }
  epoll_event events[64];
  while (frames_done < want && live > 0) {
    int nev = epoll_wait(epfd, events, 64, 10000);
    if (nev <= 0) break;  // stalled server: bail with what we have
    for (int e = 0; e < nev; e++) {
      size_t ci = events[e].data.u32;
      LgConn& c = conns[ci];
      if (c.dead) continue;
      if (events[e].events & EPOLLOUT) flush_conn(ci);
      uint8_t buf[65536];
      for (;;) {
        ssize_t r = ::recv(c.fd, buf, sizeof buf, 0);
        if (r > 0) {
          c.in.insert(c.in.end(), buf, buf + r);
          continue;
        }
        if (r == 0 || (errno != EAGAIN && errno != EWOULDBLOCK)) {
          epoll_ctl(epfd, EPOLL_CTL_DEL, c.fd, nullptr);
          c.dead = true;
          live--;
        }
        break;
      }
      int completed = 0;
      for (;;) {
        size_t avail = c.in.size() - c.in_off;
        if (avail < 4) break;
        uint32_t len = rd_u32(c.in.data() + c.in_off);
        if (avail < 4 + size_t(len)) break;
        const uint8_t* rbody = c.in.data() + c.in_off + 4;
        if (len >= kBodyOff + kBulkRespHead &&
            rbody[5] == RESP_BULK) {
          uint32_t rn = rd_u32(rbody + kBodyOff + 1);
          const uint8_t* bits = rbody + kBodyOff + kBulkRespHead;
          size_t nbits = (size_t(rn) + 7) / 8;
          if (len >= kBodyOff + kBulkRespHead + nbits) {
            for (size_t bi = 0; bi < nbits; bi++) {
              granted += __builtin_popcount(bits[bi]);
            }
          }
        }
        c.in_off += 4 + len;
        frames_done++;
        c.recvd++;
        completed++;
      }
      if (c.in_off == c.in.size()) {
        c.in.clear();
        c.in_off = 0;
      }
      if (completed > 0) send_frames(ci, completed);
    }
  }
  *out_elapsed_s = double(now_ns() - t0) * 1e-9;
  *out_frames = frames_done;
  *out_rows = frames_done * (long long)rows_per_frame;
  *out_granted = granted;
  for (auto& c : conns) ::close(c.fd);
  ::close(epfd);
  return 0;
}

// uring twin of fe_lg_bulk (round 16): identical frame template, depth
// pipelining, and accounting — the transport is ONE ring driving every
// connection, so a reply burst costs one enter instead of a recv+send
// pair per connection (in r11 the epoll loadgen's own syscall bill was
// part of the measured ceiling). Per connection at most one SEND and
// one RECV op are in flight; a 10 s TIMEOUT op mirrors the epoll lane's
// stalled-server bail. Returns -2 when the kernel lacks the uring
// feature level (callers fall back to fe_lg_bulk), else 0/-1 with the
// same contract.
int fe_lg_bulk_uring(const char* host, int port, int n_conns, int depth,
                     int frames_per_conn, int rows_per_frame, int keyspace,
                     double a, double b, double* out_elapsed_s,
                     long long* out_frames, long long* out_rows,
                     long long* out_granted) {
  {
    std::string reason;
    if (uring_probe(&reason) == 0) return -2;
  }
  if (n_conns <= 0 || rows_per_frame <= 0 || keyspace <= 0) return -1;
  DrlUringParams p{};
  p.flags = kUringSetupCqsize | kUringSetupClamp;
  unsigned sq_want = 64;
  while (sq_want < unsigned(2 * n_conns + 8) && sq_want < 4096) {
    sq_want <<= 1;
  }
  p.cq_entries = sq_want * 2;
  int rfd = sys_uring_setup(sq_want, &p);
  if (rfd < 0) return -2;
  size_t sq_len = size_t(p.sq_off.array) + p.sq_entries * sizeof(uint32_t);
  size_t cq_len = size_t(p.cq_off.cqes) + p.cq_entries * sizeof(DrlCqe);
  bool single = (p.features & kUringFeatSingleMmap) != 0;
  if (single) sq_len = cq_len = std::max(sq_len, cq_len);
  void* sqm = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_POPULATE, rfd, long(kUringOffSqRing));
  void* cqm = single ? sqm
                     : mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                            MAP_SHARED | MAP_POPULATE, rfd,
                            long(kUringOffCqRing));
  size_t sqes_len = p.sq_entries * sizeof(DrlSqe);
  void* sqesm = mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
                     MAP_SHARED | MAP_POPULATE, rfd, long(kUringOffSqes));
  if (sqm == MAP_FAILED || cqm == MAP_FAILED || sqesm == MAP_FAILED) {
    if (sqm != MAP_FAILED) munmap(sqm, sq_len);
    if (cqm != MAP_FAILED && cqm != sqm) munmap(cqm, cq_len);
    if (sqesm != MAP_FAILED) munmap(sqesm, sqes_len);
    ::close(rfd);
    return -2;
  }
  auto* sqb = static_cast<uint8_t*>(sqm);
  auto* sq_head =
      reinterpret_cast<std::atomic<uint32_t>*>(sqb + p.sq_off.head);
  auto* sq_tail =
      reinterpret_cast<std::atomic<uint32_t>*>(sqb + p.sq_off.tail);
  uint32_t sq_mask = *reinterpret_cast<uint32_t*>(sqb + p.sq_off.ring_mask);
  uint32_t* sq_array = reinterpret_cast<uint32_t*>(sqb + p.sq_off.array);
  DrlSqe* sqes = static_cast<DrlSqe*>(sqesm);
  auto* cqb = static_cast<uint8_t*>(cqm);
  auto* cq_head =
      reinterpret_cast<std::atomic<uint32_t>*>(cqb + p.cq_off.head);
  auto* cq_tail =
      reinterpret_cast<std::atomic<uint32_t>*>(cqb + p.cq_off.tail);
  uint32_t cq_mask = *reinterpret_cast<uint32_t*>(cqb + p.cq_off.ring_mask);
  DrlCqe* cqes = reinterpret_cast<DrlCqe*>(cqb + p.cq_off.cqes);
  uint32_t staged = 0;
  auto get_sqe = [&]() -> DrlSqe* {
    uint32_t tail = sq_tail->load(std::memory_order_relaxed);
    uint32_t head = sq_head->load(std::memory_order_acquire);
    if (tail - head >= sq_mask + 1) return nullptr;
    uint32_t idx = tail & sq_mask;
    DrlSqe* s = &sqes[idx];
    std::memset(s, 0, sizeof *s);
    sq_array[idx] = idx;
    sq_tail->store(tail + 1, std::memory_order_release);
    staged++;
    return s;
  };
  auto cleanup = [&]() {
    if (cqm != sqm) munmap(cqm, cq_len);
    munmap(sqm, sq_len);
    munmap(sqesm, sqes_len);
    ::close(rfd);
  };
  std::vector<LgConn> conns{size_t(n_conns)};
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    cleanup();
    return -1;
  }
  for (int i = 0; i < n_conns; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0) {
      for (int j = 0; j < i; j++) ::close(conns[size_t(j)].fd);
      ::close(fd);
      cleanup();
      return -1;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    // Sockets stay blocking: a ring op parks in the kernel instead of
    // returning EAGAIN, so no EPOLLOUT staging machinery is needed.
    conns[size_t(i)].fd = fd;
  }
  // Frame template — byte-identical to fe_lg_bulk's (the server replies
  // are compared across loadgens in the parity rig).
  uint64_t n = uint64_t(rows_per_frame);
  std::string body;
  body.push_back(char(kVersion));
  wr_u32(&body, 0);  // seq, patched per send at offset 1
  body.push_back(char(OP_ACQUIRE_MANY));
  body.push_back(char(kBulkFlagRemaining));
  wr_f64(&body, a);
  wr_f64(&body, b);
  wr_u32(&body, uint32_t(n));
  std::string blob;
  std::vector<uint16_t> klens(n);
  for (uint64_t i = 0; i < n; i++) {
    std::string key = "b" + std::to_string(i % uint64_t(keyspace));
    klens[i] = uint16_t(key.size());
    blob += key;
  }
  body.append(reinterpret_cast<const char*>(klens.data()), 2 * n);
  body += blob;
  for (uint64_t i = 0; i < n; i++) wr_u32(&body, 1);  // unit counts
  std::string frame;
  wr_u32(&frame, uint32_t(body.size()));
  frame += body;
  constexpr size_t kSeqOff = 5;
  std::vector<std::string> outq(static_cast<size_t>(n_conns));
  std::vector<size_t> outq_off(static_cast<size_t>(n_conns), 0);
  std::vector<uint8_t> send_busy(static_cast<size_t>(n_conns), 0);
  std::vector<uint8_t> recv_busy(static_cast<size_t>(n_conns), 0);
  std::vector<std::vector<uint8_t>> rbuf(
      static_cast<size_t>(n_conns), std::vector<uint8_t>(65536));
  auto arm_send = [&](size_t ci) {
    LgConn& c = conns[ci];
    if (send_busy[ci] != 0 || c.dead) return;
    if (outq_off[ci] >= outq[ci].size()) {
      outq[ci].clear();
      outq_off[ci] = 0;
      return;
    }
    DrlSqe* s = get_sqe();
    if (s == nullptr) return;  // retried when the op count drops
    s->opcode = kOpSend;
    s->fd = c.fd;
    s->addr = uint64_t(
        reinterpret_cast<uintptr_t>(outq[ci].data() + outq_off[ci]));
    s->len = uint32_t(outq[ci].size() - outq_off[ci]);
    s->op_flags = MSG_NOSIGNAL;
    s->user_data = uring_ud(kUdSend, ci);
    send_busy[ci] = 1;
  };
  auto arm_recv = [&](size_t ci) {
    LgConn& c = conns[ci];
    if (recv_busy[ci] != 0 || c.dead) return;
    DrlSqe* s = get_sqe();
    if (s == nullptr) return;
    s->opcode = kOpRecv;
    s->fd = c.fd;
    s->addr = uint64_t(reinterpret_cast<uintptr_t>(rbuf[ci].data()));
    s->len = uint32_t(rbuf[ci].size());
    s->user_data = uring_ud(kUdRecv, ci);
    recv_busy[ci] = 1;
  };
  auto send_frames = [&](size_t ci, int count) {
    LgConn& c = conns[ci];
    for (int d = 0; d < count && c.sent < frames_per_conn; d++) {
      uint32_t seq = uint32_t(c.sent++);
      std::memcpy(&frame[kSeqOff], &seq, 4);
      outq[ci] += frame;
    }
    arm_send(ci);
  };
  DrlKTimespec bail_ts{10, 0};  // the epoll lane's 10 s stall bail
  auto arm_bail = [&]() {
    DrlSqe* s = get_sqe();
    if (s == nullptr) return;
    s->opcode = kOpTimeout;
    s->addr = uint64_t(reinterpret_cast<uintptr_t>(&bail_ts));
    s->len = 1;
    s->user_data = uring_ud(kUdTfRead, 0);  // kind reuse: the timer slot
  };
  long long frames_done = 0, granted = 0;
  long long bail_mark = -1;
  int live = n_conns;
  const long long want = (long long)n_conns * frames_per_conn;
  uint64_t t0 = now_ns();
  for (size_t ci = 0; ci < size_t(n_conns); ci++) {
    send_frames(ci, depth);
    arm_recv(ci);
  }
  arm_bail();
  bool stalled = false;
  while (frames_done < want && live > 0 && !stalled) {
    int rc = sys_uring_enter(rfd, staged, 1, kUringEnterGetevents);
    if (rc < 0) {
      if (errno == EINTR || errno == EBUSY || errno == EAGAIN) continue;
      break;
    }
    staged -= uint32_t(rc) > staged ? staged : uint32_t(rc);
    uint32_t head = cq_head->load(std::memory_order_relaxed);
    uint32_t tail = cq_tail->load(std::memory_order_acquire);
    while (head != tail) {
      DrlCqe cqe = cqes[head & cq_mask];
      head++;
      cq_head->store(head, std::memory_order_release);
      uint64_t kind = cqe.user_data >> 56;
      size_t ci = size_t(cqe.user_data & ((1ull << 56) - 1));
      if (kind == kUdTfRead) {  // the 10 s stall bail
        if (frames_done == bail_mark) {
          stalled = true;
          break;
        }
        bail_mark = frames_done;
        arm_bail();
        tail = cq_tail->load(std::memory_order_acquire);
        continue;
      }
      LgConn& c = conns[ci];
      if (kind == kUdSend) {
        send_busy[ci] = 0;
        if (cqe.res < 0) {
          if (!c.dead) {
            c.dead = true;
            live--;
          }
        } else if (!c.dead) {
          outq_off[ci] += size_t(cqe.res);
          arm_send(ci);
        }
        tail = cq_tail->load(std::memory_order_acquire);
        continue;
      }
      // kUdRecv
      recv_busy[ci] = 0;
      if (cqe.res <= 0) {
        if (!c.dead) {
          c.dead = true;
          live--;
        }
        tail = cq_tail->load(std::memory_order_acquire);
        continue;
      }
      c.in.insert(c.in.end(), rbuf[ci].data(), rbuf[ci].data() + cqe.res);
      int completed = 0;
      for (;;) {
        size_t avail = c.in.size() - c.in_off;
        if (avail < 4) break;
        uint32_t len = rd_u32(c.in.data() + c.in_off);
        if (avail < 4 + size_t(len)) break;
        const uint8_t* rbody = c.in.data() + c.in_off + 4;
        if (len >= kBodyOff + kBulkRespHead && rbody[5] == RESP_BULK) {
          uint32_t rn = rd_u32(rbody + kBodyOff + 1);
          const uint8_t* bits = rbody + kBodyOff + kBulkRespHead;
          size_t nbits = (size_t(rn) + 7) / 8;
          if (len >= kBodyOff + kBulkRespHead + nbits) {
            for (size_t bi = 0; bi < nbits; bi++) {
              granted += __builtin_popcount(bits[bi]);
            }
          }
        }
        c.in_off += 4 + len;
        frames_done++;
        c.recvd++;
        completed++;
      }
      if (c.in_off == c.in.size()) {
        c.in.clear();
        c.in_off = 0;
      }
      if (completed > 0) send_frames(ci, completed);
      arm_recv(ci);
      tail = cq_tail->load(std::memory_order_acquire);
    }
  }
  *out_elapsed_s = double(now_ns() - t0) * 1e-9;
  *out_frames = frames_done;
  *out_rows = frames_done * (long long)rows_per_frame;
  *out_granted = granted;
  for (auto& c : conns) ::close(c.fd);
  cleanup();
  return 0;
}

}  // extern "C"
