// Native key directory: the host-runtime hot path of the TPU bucket store.
//
// Role: the (key string -> device slot) map that the reference kept inside
// Redis's own keyspace (one hash per bucket key) lives host-side here,
// fronting the HBM slot arrays. Every micro-batch flush resolves up to
// max_batch keys; this directory does that in one C call instead of a
// Python dict loop — open addressing with linear probing, FNV-1a hashing,
// an append-only key arena, an explicit free-list of device slots, and a
// slot->bucket reverse index so TTL sweeps can evict by slot id.
//
// Plain C ABI (extern "C") consumed via ctypes; no Python.h dependency, so
// it builds with a bare `g++ -O3 -shared -fPIC`.

#ifdef DRL_WITH_PYTHON
#include <Python.h>
#endif

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

struct Bucket {
  uint64_t hash;     // 0 = empty (hashes are forced nonzero)
  uint64_t key_off;  // offset into arena
  uint32_t key_len;
  int32_t slot;
};

struct Directory {
  std::vector<Bucket> table;     // power-of-two sized
  std::vector<char> arena;       // concatenated key bytes
  std::vector<int32_t> free_slots;   // LIFO free-list of device slots
  std::vector<int32_t> slot_to_bucket;  // slot id -> table index (-1 = none)
  uint64_t mask = 0;
  int64_t size = 0;
  uint64_t live_bytes = 0;  // arena bytes owned by live entries

  explicit Directory(int64_t n_slots) {
    uint64_t cap = 64;
    while (cap < static_cast<uint64_t>(n_slots) * 2) cap <<= 1;
    table.assign(cap, Bucket{0, 0, 0, -1});
    mask = cap - 1;
    arena.reserve(1 << 16);
    free_slots.reserve(n_slots);
    // Match the Python store's allocation order (descending pop -> slot 0
    // first) so directory behavior is bit-identical across backends.
    for (int64_t s = n_slots - 1; s >= 0; --s)
      free_slots.push_back(static_cast<int32_t>(s));
    slot_to_bucket.assign(n_slots, -1);
  }
};

// 64-bit key fingerprint (FNV-1a with the all-zero remap) for the
// device-resident fingerprint directory. ONE definition shared by the
// blob and pylist entry points — fingerprints live in device tables and
// checkpoints, so every process must hash bit-identically (the Python
// fallback _fp64_py mirrors this; note fnv1a() below is NOT the same
// function: its |1 remap serves the host directory's empty sentinel).
inline uint64_t fp64_of(const char* key, int64_t len) {
  constexpr uint64_t kFnvOffset = 1469598103934665603ULL;
  constexpr uint64_t kFnvPrime = 1099511628211ULL;
  uint64_t h = kFnvOffset;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(key[i]);
    h *= kFnvPrime;
  }
  if (h == 0) h = kFnvOffset;
  return h;
}

inline uint64_t fnv1a(const char* data, uint32_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ULL;
  }
  return h | 1;  // nonzero: 0 marks an empty bucket
}

// Rebuild the arena with only live keys. The arena is append-only during
// normal operation; without this, memory would grow with total distinct
// keys ever seen rather than live keys under key churn (the designed
// workload: TTL sweeps evict, new keys arrive).
void compact_arena(Directory* d) {
  std::vector<char> fresh;
  fresh.reserve(d->live_bytes);
  for (Bucket& b : d->table) {
    if (b.hash == 0) continue;
    uint64_t off = fresh.size();
    fresh.insert(fresh.end(), d->arena.data() + b.key_off,
                 d->arena.data() + b.key_off + b.key_len);
    b.key_off = off;
  }
  d->arena = std::move(fresh);
}

void maybe_compact(Directory* d) {
  if (d->arena.size() > (1 << 16) &&
      d->arena.size() > d->live_bytes * 2)
    compact_arena(d);
}

void rehash(Directory* d) {
  std::vector<Bucket> old = std::move(d->table);
  d->table.assign(old.size() * 2, Bucket{0, 0, 0, -1});
  d->mask = d->table.size() - 1;
  for (const Bucket& b : old) {
    if (b.hash == 0) continue;
    uint64_t i = b.hash & d->mask;
    while (d->table[i].hash != 0) i = (i + 1) & d->mask;
    d->table[i] = b;
    d->slot_to_bucket[b.slot] = static_cast<int32_t>(i);
  }
}

// CRC-32 (ISO-HDLC, the zlib/crc32 polynomial) for shard routing: the
// sharded store routes key -> shard by crc32(key) % n_shards on every
// client host, so the C path must agree bit-for-bit with Python's
// zlib.crc32 (sharded_store.shard_of_key).
uint32_t g_crc_table[256];
bool g_crc_ready = false;

void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
    g_crc_table[i] = c;
  }
  g_crc_ready = true;
}

inline uint32_t crc32_of(const char* data, int64_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (int64_t i = 0; i < len; ++i)
    c = g_crc_table[(c ^ static_cast<unsigned char>(data[i])) & 0xFFu] ^
        (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Find the table index holding `key`, or the empty index where it belongs.
inline uint64_t probe(const Directory* d, uint64_t h, const char* key,
                      uint32_t len) {
  uint64_t i = h & d->mask;
  while (true) {
    const Bucket& b = d->table[i];
    if (b.hash == 0) return i;
    if (b.hash == h && b.key_len == len &&
        std::memcmp(d->arena.data() + b.key_off, key, len) == 0)
      return i;
    i = (i + 1) & d->mask;
  }
}

// Resolve-or-allocate one key against one directory (shared by every
// batch-resolve entry point — the bookkeeping must stay identical across
// them). Returns the slot, or -1 when the free-list is dry.
inline int32_t resolve_one(Directory* d, const char* key, uint32_t len) {
  uint64_t hash = fnv1a(key, len);
  uint64_t i = probe(d, hash, key, len);
  if (d->table[i].hash != 0) return d->table[i].slot;
  if (d->free_slots.empty()) return -1;
  int32_t slot = d->free_slots.back();
  d->free_slots.pop_back();
  uint64_t off = d->arena.size();
  d->arena.insert(d->arena.end(), key, key + len);
  d->table[i] = Bucket{hash, off, len, slot};
  d->slot_to_bucket[slot] = static_cast<int32_t>(i);
  d->live_bytes += len;
  ++d->size;
  if (static_cast<uint64_t>(d->size) * 10 > d->table.size() * 7) rehash(d);
  return slot;
}

}  // namespace

extern "C" {

void* dir_new(int64_t n_slots) { return new Directory(n_slots); }

void dir_free(void* h) { delete static_cast<Directory*>(h); }

int64_t dir_size(void* h) { return static_cast<Directory*>(h)->size; }

int64_t dir_free_count(void* h) {
  return static_cast<int64_t>(static_cast<Directory*>(h)->free_slots.size());
}

// Resolve a batch of keys to slots, allocating from the free-list on miss.
// keys = concatenated UTF-8 bytes; offsets[i]..offsets[i+1] bounds key i
// (offsets has n+1 entries). out_slots[i] receives the slot, or -1 if the
// free-list ran dry at that point (caller sweeps/grows and re-resolves the
// tail). Returns the number of unresolved (-1) entries.
int64_t dir_resolve_batch(void* h, const char* keys, const int64_t* offsets,
                          int64_t n, int32_t* out_slots) {
  Directory* d = static_cast<Directory*>(h);
  int64_t unresolved = 0;
  for (int64_t k = 0; k < n; ++k) {
    const char* key = keys + offsets[k];
    uint32_t len = static_cast<uint32_t>(offsets[k + 1] - offsets[k]);
    out_slots[k] = resolve_one(d, key, len);
    if (out_slots[k] < 0) ++unresolved;
  }
  return unresolved;
}

// Lookup without allocation; returns slot or -1.
int32_t dir_lookup(void* h, const char* key, int64_t len) {
  Directory* d = static_cast<Directory*>(h);
  uint64_t hash = fnv1a(key, static_cast<uint32_t>(len));
  uint64_t i = probe(d, hash, key, static_cast<uint32_t>(len));
  return d->table[i].hash == 0 ? -1 : d->table[i].slot;
}

// Evict entries by device slot id (TTL sweep): for each dead slot, remove
// its key (if mapped) and return the slot to the free-list. Tombstone-free
// deletion via backward-shift, keeping probe chains intact. Returns the
// number of entries actually removed.
int64_t dir_remove_slots(void* h, const int32_t* dead, int64_t n_dead) {
  Directory* d = static_cast<Directory*>(h);
  int64_t removed = 0;
  for (int64_t k = 0; k < n_dead; ++k) {
    int32_t slot = dead[k];
    if (slot < 0 ||
        static_cast<size_t>(slot) >= d->slot_to_bucket.size())
      continue;
    int32_t ti = d->slot_to_bucket[slot];
    if (ti < 0) continue;  // unmapped: skip — freeing it could double-free
    // Backward-shift deletion starting at ti.
    uint64_t i = static_cast<uint64_t>(ti);
    d->live_bytes -= d->table[i].key_len;
    d->slot_to_bucket[slot] = -1;
    d->free_slots.push_back(slot);
    --d->size;
    ++removed;
    uint64_t j = i;
    while (true) {
      j = (j + 1) & d->mask;
      Bucket& bj = d->table[j];
      if (bj.hash == 0) break;
      uint64_t home = bj.hash & d->mask;
      // Can bj move into the hole at i? Yes iff i is cyclically between
      // home and j.
      bool movable = (i <= j) ? (home <= i || home > j)
                              : (home <= i && home > j);
      if (movable) {
        d->table[i] = bj;
        d->slot_to_bucket[bj.slot] = static_cast<int32_t>(i);
        i = j;
      }
    }
    d->table[i] = Bucket{0, 0, 0, -1};
  }
  maybe_compact(d);
  return removed;
}

// Extend slot capacity after a table grow: slots [start, end) join the
// free-list in descending order (matching the Python store).
void dir_add_slots(void* h, int32_t start, int32_t end) {
  Directory* d = static_cast<Directory*>(h);
  d->slot_to_bucket.resize(end, -1);
  for (int32_t s = end - 1; s >= start; --s) d->free_slots.push_back(s);
}

// Restore support: bind `key` to a specific `slot` (checkpoint restore
// rebuilds the directory from a saved mapping; the caller re-seeds the
// free-list by NOT calling this for free slots — see dir_set_free below).
// Returns 0 on success, -1 if the key already exists with another slot.
int32_t dir_insert(void* h, const char* key, int64_t len, int32_t slot) {
  Directory* d = static_cast<Directory*>(h);
  uint64_t hash = fnv1a(key, static_cast<uint32_t>(len));
  uint64_t i = probe(d, hash, key, static_cast<uint32_t>(len));
  if (d->table[i].hash != 0) return d->table[i].slot == slot ? 0 : -1;
  uint64_t off = d->arena.size();
  d->arena.insert(d->arena.end(), key, key + len);
  d->table[i] = Bucket{hash, off, static_cast<uint32_t>(len), slot};
  if (static_cast<size_t>(slot) >= d->slot_to_bucket.size())
    d->slot_to_bucket.resize(slot + 1, -1);
  d->slot_to_bucket[slot] = static_cast<int32_t>(i);
  d->live_bytes += static_cast<uint64_t>(len);
  ++d->size;
  if (static_cast<uint64_t>(d->size) * 10 > d->table.size() * 7) rehash(d);
  return 0;
}

// Replace the free-list wholesale (restore path). Slots are pushed in the
// given order; the LAST entry pops first.
void dir_set_free(void* h, const int32_t* slots, int64_t n) {
  Directory* d = static_cast<Directory*>(h);
  d->free_slots.assign(slots, slots + n);
}

// Snapshot support: dump all (key, slot) pairs. Caller passes buffers
// sized from dir_size()/dir_arena_size(); layout mirrors resolve input
// (concatenated keys + n+1 offsets + slots). Returns the entry count.
int64_t dir_arena_bytes(void* h) {
  Directory* d = static_cast<Directory*>(h);
  int64_t total = 0;
  for (const Bucket& b : d->table)
    if (b.hash != 0) total += b.key_len;
  return total;
}

int64_t dir_dump(void* h, char* keys_out, int64_t* offsets_out,
                 int32_t* slots_out) {
  Directory* d = static_cast<Directory*>(h);
  int64_t n = 0, off = 0;
  for (const Bucket& b : d->table) {
    if (b.hash == 0) continue;
    std::memcpy(keys_out + off, d->arena.data() + b.key_off, b.key_len);
    offsets_out[n] = off;
    slots_out[n] = b.slot;
    off += b.key_len;
    ++n;
  }
  offsets_out[n] = off;
  return n;
}

// Batch shard routing: out[i] = crc32(key_i) % n_shards. Standalone (no
// directory handle) — routing happens before any per-shard directory is
// touched. keys/offsets layout as in dir_resolve_batch.
void dir_route_batch(const char* keys, const int64_t* offsets, int64_t n,
                     int32_t n_shards, int32_t* out) {
  if (!g_crc_ready) crc_init();
  for (int64_t k = 0; k < n; ++k)
    out[k] = static_cast<int32_t>(
        crc32_of(keys + offsets[k], offsets[k + 1] - offsets[k]) %
        static_cast<uint32_t>(n_shards));
}

// Blob variants of the sharded resolve and the fp64 hash: the serving
// path's zero-copy lane (wire.KeyBlob) hands a bulk frame's key bytes
// straight through — no Python strings, no GIL needed, plain C ABI.
int64_t dir_resolve_sharded_batch(const char* blob, const int64_t* offsets,
                                  int64_t n, void** handles,
                                  int32_t n_shards, int32_t* out_shards,
                                  int32_t* out_locals) {
  if (!g_crc_ready) crc_init();
  int64_t unresolved = 0;
  for (int64_t k = 0; k < n; ++k) {
    const char* key = blob + offsets[k];
    uint32_t len = static_cast<uint32_t>(offsets[k + 1] - offsets[k]);
    uint32_t shard = crc32_of(key, len) % static_cast<uint32_t>(n_shards);
    out_shards[k] = static_cast<int32_t>(shard);
    Directory* d = static_cast<Directory*>(handles[shard]);
    out_locals[k] = resolve_one(d, key, len);
    if (out_locals[k] < 0) ++unresolved;
  }
  return unresolved;
}

int64_t dir_fp64_batch(const char* blob, const int64_t* offsets, int64_t n,
                       uint32_t* out) {
  for (int64_t k = 0; k < n; ++k) {
    uint64_t h = fp64_of(blob + offsets[k], offsets[k + 1] - offsets[k]);
    out[2 * k] = static_cast<uint32_t>(h);
    out[2 * k + 1] = static_cast<uint32_t>(h >> 32);
  }
  return 0;
}

#ifdef DRL_WITH_PYTHON
// Zero-copy batch resolve over a Python list[str]: reads each key's
// cached UTF-8 via PyUnicode_AsUTF8AndSize — no encode, no concat, no
// offset array. Must be called with the GIL held (load via ctypes.PyDLL).
// Returns unresolved count, or -1 on a non-str element (with a Python
// error set? no — ctypes PyDLL propagates it poorly; we just return -1
// and let the caller fall back).
int64_t dir_resolve_pylist(void* h, PyObject* keys, int32_t* out_slots) {
  Directory* d = static_cast<Directory*>(h);
  Py_ssize_t n = PyList_GET_SIZE(keys);
  int64_t unresolved = 0;
  for (Py_ssize_t k = 0; k < n; ++k) {
    PyObject* s = PyList_GET_ITEM(keys, k);
    Py_ssize_t len;
    const char* key = PyUnicode_AsUTF8AndSize(s, &len);
    if (key == nullptr) {
      PyErr_Clear();
      return -1;
    }
    out_slots[k] = resolve_one(d, key, static_cast<uint32_t>(len));
    if (out_slots[k] < 0) ++unresolved;
  }
  return unresolved;
}

// Fused route+resolve over a Python list[str]: for each key, crc32 picks
// the shard, then that shard's directory resolves (allocating on miss) —
// the whole mesh-store key resolution in ONE C pass instead of a route
// call plus per-shard grouping and resolve calls on the Python side.
// handles = n_shards Directory*; out_shards/out_locals get the routing
// and the shard-local slot (-1 when that shard's free-list ran dry —
// caller sweeps/grows and re-resolves). Returns the unresolved count, or
// -1 on a non-str element (caller falls back to the split path).
int64_t dir_resolve_sharded_pylist(PyObject* keys, void** handles,
                                   int32_t n_shards, int32_t* out_shards,
                                   int32_t* out_locals) {
  if (!g_crc_ready) crc_init();
  Py_ssize_t n = PyList_GET_SIZE(keys);
  int64_t unresolved = 0;
  for (Py_ssize_t k = 0; k < n; ++k) {
    PyObject* s = PyList_GET_ITEM(keys, k);
    Py_ssize_t len;
    const char* key = PyUnicode_AsUTF8AndSize(s, &len);
    if (key == nullptr) {
      PyErr_Clear();
      return -1;
    }
    uint32_t shard = crc32_of(key, len) % static_cast<uint32_t>(n_shards);
    out_shards[k] = static_cast<int32_t>(shard);
    Directory* d = static_cast<Directory*>(handles[shard]);
    out_locals[k] = resolve_one(d, key, static_cast<uint32_t>(len));
    if (out_locals[k] < 0) ++unresolved;
  }
  return unresolved;
}

// 64-bit key fingerprints (FNV-1a) over a Python list[str], for the
// device-resident fingerprint directory: the DEVICE probes/inserts on
// these, so the host needs only this single hashing pass per batch — no
// host-side table at all. out[2k]/out[2k+1] = low/high u32 halves. An
// all-zero fingerprint is the table's EMPTY sentinel, so the (2^-64)
// hash that lands there is remapped to the FNV offset basis. Returns 0,
// or -1 on a non-str element (caller falls back to the Python hasher).
int64_t dir_fp64_pylist(PyObject* keys, uint32_t* out) {
  Py_ssize_t n = PyList_GET_SIZE(keys);
  for (Py_ssize_t k = 0; k < n; ++k) {
    PyObject* s = PyList_GET_ITEM(keys, k);
    Py_ssize_t len;
    const char* key = PyUnicode_AsUTF8AndSize(s, &len);
    if (key == nullptr) {
      PyErr_Clear();
      return -1;
    }
    uint64_t h = fp64_of(key, len);
    out[2 * k] = static_cast<uint32_t>(h);
    out[2 * k + 1] = static_cast<uint32_t>(h >> 32);
  }
  return 0;
}

// Zero-copy batch shard routing over a Python list[str] (GIL held, as
// dir_resolve_pylist). Returns 0, or -1 on a non-str element (caller
// falls back to the encode path).
int64_t dir_route_pylist(PyObject* keys, int32_t n_shards, int32_t* out) {
  if (!g_crc_ready) crc_init();
  Py_ssize_t n = PyList_GET_SIZE(keys);
  for (Py_ssize_t k = 0; k < n; ++k) {
    PyObject* s = PyList_GET_ITEM(keys, k);
    Py_ssize_t len;
    const char* key = PyUnicode_AsUTF8AndSize(s, &len);
    if (key == nullptr) {
      PyErr_Clear();
      return -1;
    }
    out[k] = static_cast<int32_t>(crc32_of(key, len) %
                                  static_cast<uint32_t>(n_shards));
  }
  return 0;
}
#endif  // DRL_WITH_PYTHON

}  // extern "C"
